"""Serving observability: a small metrics registry with Prometheus text
exposition (no client library dependency — the format is plain text).

Three instrument kinds: monotonically increasing ``Counter``, last-value
``Gauge`` and the fixed-bucket ``LatencyHistogram`` from utils/profiling.py
(shared with the Evaluator's per-call timing).  Counters and gauges can be
registered with ``labels=(...)`` — a label FAMILY whose per-label-set
children are created on first use — so hot counters split by dimension
(``serve_requests_total{endpoint=,outcome=}``,
``serve_compile_cache_misses_total{bucket=,iters=,mode=}``) while the
render stays valid Prometheus 0.0.4 (label values escaped, one TYPE block
per family; validated by raftstereo_tpu/obs/prom.py in the tier-1 tests).
``MetricsRegistry.render`` emits the text format Prometheus scrapes from
``GET /metrics``:

    # HELP serve_requests_total ...
    # TYPE serve_requests_total counter
    serve_requests_total{endpoint="predict",outcome="ok"} 42
    serve_request_latency_seconds_bucket{le="0.1"} 17
    ...

``ServeMetrics`` bundles every instrument the serving subsystem records, so
the engine, batcher and HTTP layer share one object and ``/metrics`` is one
render call.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.profiling import LatencyHistogram

__all__ = ["ClusterMetrics", "Counter", "Gauge", "LabelFamily",
           "MetricsRegistry", "ServeMetrics"]


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0  # guarded_by: _lock

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:  # a torn read mid-inc would render a bogus sample
            return self._value


class Gauge:
    """Last-value instrument (Prometheus ``gauge``).

    Locked ``set`` AND ``add``: read-modify-write callers (live session
    counts, in-flight gauges) must not lose updates under the threaded
    HTTP front-end, and ``g.set(g.value + 1)`` races exactly there.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0  # guarded_by: _lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += delta

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class LabelFamily:
    """A labeled metric family: ``family.labels(k=v, ...)`` returns the
    child instrument for that label set, creating it on first use.

    ``value`` sums the children — the label-blind total, which is also
    what pre-label callers and tests read.  Children render as one series
    per label set under a single HELP/TYPE block.
    """

    def __init__(self, make_child, label_names: Sequence[str]):
        assert label_names, "a family needs at least one label"
        self._make = make_child
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        # child instruments by label values  # guarded_by: _lock
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, **kv):
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"labels {sorted(kv)} != declared {sorted(self.label_names)}")
        key = tuple(str(kv[k]) for k in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            return child

    def series(self) -> List[Tuple[Tuple[str, ...], object]]:
        """(label_values, child) pairs in first-use order (snapshot)."""
        with self._lock:
            return list(self._children.items())

    @property
    def value(self) -> float:
        return sum(c.value for _, c in self.series())


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return format(v, ".9g")


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class MetricsRegistry:
    """Ordered name -> instrument registry with Prometheus text rendering."""

    def __init__(self):
        self._lock = threading.Lock()
        # (kind, name, help, instrument)  # guarded_by: _lock
        self._entries: List[Tuple[str, str, str, object]] = []

    def _register(self, kind: str, name: str, help_: str, obj):
        with self._lock:
            if any(e[1] == name for e in self._entries):
                raise ValueError(f"metric {name!r} already registered")
            self._entries.append((kind, name, help_, obj))
        return obj

    def counter(self, name: str, help_: str,
                labels: Sequence[str] = ()):
        obj = LabelFamily(Counter, labels) if labels else Counter()
        return self._register("counter", name, help_, obj)

    def gauge(self, name: str, help_: str, labels: Sequence[str] = ()):
        obj = LabelFamily(Gauge, labels) if labels else Gauge()
        return self._register("gauge", name, help_, obj)

    def histogram(self, name: str, help_: str,
                  bounds=None, lo: float = 1e-4,
                  hi: float = 60.0) -> LatencyHistogram:
        return self._register("histogram", name, help_,
                              LatencyHistogram(bounds=bounds, lo=lo, hi=hi))

    def entries(self) -> List[Tuple[str, str, str, object]]:
        """(kind, name, help, instrument) snapshot — for the name lint
        (scripts/check_metrics.py) and exporters."""
        with self._lock:
            return list(self._entries)

    def render(self) -> str:
        """Prometheus text exposition format, version 0.0.4."""
        lines: List[str] = []
        for kind, name, help_, obj in self.entries():
            lines.append(f"# HELP {name} {_escape_help(help_)}")
            lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                # One atomic snapshot: _count must equal the +Inf bucket.
                pairs, count, total = obj.prometheus()
                for bound, cum in pairs:
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}')
                lines.append(f"{name}_sum {format(total, '.9g')}")
                lines.append(f"{name}_count {count}")
            elif isinstance(obj, LabelFamily):
                # A family with no children renders HELP/TYPE only —
                # legal, and keeps scrape schemas stable from startup.
                for values, child in obj.series():
                    labelset = ",".join(
                        f'{k}="{_escape_label(v)}"'
                        for k, v in zip(obj.label_names, values))
                    lines.append(f"{name}{{{labelset}}} {_fmt(child.value)}")
            else:
                lines.append(f"{name} {_fmt(obj.value)}")
        return "\n".join(lines) + "\n"


class ServeMetrics:
    """Every instrument the serving subsystem records, in one bundle."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or MetricsRegistry()
        self.registry = r
        self.requests = r.counter(
            "serve_requests_total",
            "requests answered by the HTTP front-end, by endpoint "
            "(predict/stream; other = POST to an unknown path) and outcome "
            "(ok/bad_request/shed/timeout/unavailable/too_large/not_found/"
            "error)",
            labels=("endpoint", "outcome"))
        self.responses = r.counter(
            "serve_responses_total", "requests answered successfully")
        self.tier_requests = r.counter(
            "serve_tier_requests_total",
            "/predict requests by resolved accuracy tier "
            "(certified/fast/turbo; 'default' = no accuracy field — the "
            "base precision path; docs/serving.md \"Accuracy tiers\")",
            labels=("tier",))
        self.shed = r.counter(
            "serve_shed_total",
            "requests rejected at admission because the queue was full")
        self.timeouts = r.counter(
            "serve_timeout_total",
            "requests that exceeded request_timeout_ms while queued")
        self.errors = r.counter(
            "serve_errors_total", "requests failed by an engine error")
        self.degraded_batches = r.counter(
            "serve_degraded_batches_total",
            "batches run at degraded_iters due to queue backlog")
        self.compile_hits = r.counter(
            "serve_compile_cache_hits_total",
            "batches dispatched to an already-compiled executable",
            labels=("bucket", "iters", "mode", "tier"))
        self.compile_misses = r.counter(
            "serve_compile_cache_misses_total",
            "batches whose (bucket, iters, precision mode) triggered an "
            "XLA compile — tier= is the resolved precision mode, so a "
            "per-tier compile under traffic is attributable",
            labels=("bucket", "iters", "mode", "tier"))
        self.queue_depth = r.gauge(
            "serve_queue_depth", "requests currently waiting in the queue")
        self.batch_size = r.histogram(
            "serve_batch_size", "real (un-padded) requests per batch",
            bounds=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64))
        self.latency = r.histogram(
            "serve_request_latency_seconds",
            "submit-to-result latency per request (queue wait + compute)")
        self.batch_latency = r.histogram(
            "serve_batch_latency_seconds",
            "engine wall-clock per dispatched batch (forward + host fetch)")
        # Temporal warm-start streaming (stream/, docs/streaming.md).
        self.stream_active = r.gauge(
            "stream_sessions_active", "live sessions in the session store")
        self.stream_warm_frames = r.counter(
            "stream_warm_frames_total",
            "frames warm-started from the previous frame's disparity")
        self.stream_cold_frames = r.counter(
            "stream_cold_frames_total",
            "frames run cold, by reason: new (no session state — includes "
            "expired/evicted sessions re-established), reset (controller "
            "cold reset), out_of_order (seq_no mismatch), resized (bucket "
            "change mid-stream)",
            labels=("reason",))
        self.stream_evicted = r.counter(
            "stream_sessions_evicted_total",
            "sessions LRU-evicted because the store hit session_limit")
        self.stream_expired = r.counter(
            "stream_sessions_expired_total",
            "sessions dropped after idling past session_ttl_s")
        self.stream_frame_iters = r.histogram(
            "stream_frame_iters", "GRU iterations run per streamed frame",
            bounds=(1, 2, 4, 8, 12, 16, 24, 32, 48, 64))
        self.stream_frame_latency = r.histogram(
            "stream_frame_latency_seconds",
            "per-frame wall-clock (warp + forward + host fetch), "
            "compile-free frames only")
        # Durable session tier (stream/tier.py, docs/streaming.md
        # "Durable sessions").
        self.stream_session_bytes = r.gauge(
            "stream_session_bytes",
            "byte-accurate total of all live session state in the "
            "in-replica store (disparity plane nbytes + fixed controller "
            "overhead per session) — the value the session_budget_mb "
            "byte-budget eviction bounds")
        self.stream_tier_pushes = r.counter(
            "stream_tier_pushes_total",
            "write-behind snapshot pushes to the session tier by outcome: "
            "ok (stored), stale (tier already held fresher state — "
            "harmless), degraded (suppressed while detached from an "
            "unreachable tier), dropped (coalescing queue overflowed; "
            "oldest pending SID discarded, its next frame re-enqueues), "
            "skipped (no exportable state at send time), error (push "
            "failed after retries; the publisher detached)",
            labels=("outcome",))
        self.stream_tier_degraded = r.counter(
            "stream_tier_degraded_total",
            "pushes suppressed or failed because the session tier was "
            "unreachable/slow — graceful degradation to local-pin "
            "behaviour, never an error; the publisher re-probes every "
            "tier_reprobe_s and re-attaches")
        self.stream_tier_attached = r.gauge(
            "stream_tier_attached",
            "1 while the write-behind publisher considers the session "
            "tier reachable, 0 while degraded to local-pin behaviour")
        # Iteration-level continuous batching (serve/sched/,
        # docs/serving.md).
        self.sched_slots_active = r.gauge(
            "sched_slots_active",
            "occupied slots across the scheduler's running batches")
        self.sched_occupancy = r.gauge(
            "sched_occupancy",
            "occupied fraction (0-1) of the running batches' slots")
        self.sched_queue_depth = r.gauge(
            "sched_queue_depth",
            "requests waiting for a slot, by priority class "
            "(high/normal/low)",
            labels=("priority",))
        self.sched_joins = r.counter(
            "sched_joins_total",
            "requests that joined a running batch at an iteration boundary")
        self.sched_leaves = r.counter(
            "sched_leaves_total",
            "requests that left a running batch (target iterations reached "
            "or deadline early exit)")
        self.sched_early_exits = r.counter(
            "sched_early_exits_total",
            "deadline-aware early exits: requests answered with the "
            "anytime result before their target iterations "
            "(meta.degraded=true)")
        self.sched_steps = r.counter(
            "sched_steps_total",
            "single-boundary step executions across running batches")
        self.sched_step_latency = r.histogram(
            "sched_step_latency_seconds",
            "engine wall-clock per scheduler step (every occupied slot "
            "advances iters_per_step iterations), compile-free steps only")
        # Speculative tier cascades (serve/cascade/, docs/serving.md
        # "Tier cascade").
        self.cascade_schedules = r.counter(
            "cascade_schedules_total",
            "completed cascade requests by canonical schedule string "
            "(deadline-degraded cheap-phase exits are NOT counted: "
            "their answer never reached the certified tier)",
            labels=("schedule",))
        self.cascade_promotions = r.counter(
            "cascade_promotions_total",
            "cheap-to-certified tier handoffs by kind: 'scheduled' at "
            "the schedule's cheap-leg boundary, 'early' when the "
            "divergence EMA crossed --cascade_divergence first",
            labels=("kind",))
        self.cascade_iterations = r.counter(
            "cascade_iterations_total",
            "GRU iterations executed for cascade slots by phase "
            "(cheap/certified) — certified over the sum is the EXECUTED "
            "fp32-iteration fraction the cascade is buying down",
            labels=("phase",))
        self.cascade_fp32_fraction = r.gauge(
            "cascade_fp32_fraction",
            "executed fp32-iteration fraction of the most recently "
            "completed cascade request (scheduled fraction when no "
            "early promotion fired)")
        # Spatial sharding (parallel/spatial.py, serve/spatial/,
        # docs/serving.md "Spatial sharding").
        self.spatial_shards = r.gauge(
            "spatial_shards",
            "spatial mesh width the engine was built with (0 = spatial "
            "sharding disabled)")
        self.spatial_requests = r.counter(
            "spatial_requests_total",
            "requests dispatched on the spatial path by outcome "
            "(ok/error/shed) — admission 400s never reach the mesh and "
            "are counted only in serve_requests_total",
            labels=("outcome",))
        self.spatial_latency = r.histogram(
            "spatial_request_latency_seconds",
            "engine wall-clock per spatial dispatch (pad + sharded "
            "forward + host fetch); the mesh is exclusive, so this is "
            "also the mesh-busy time per request")
        # Binary wire format (raftstereo_tpu/wire, docs/wire_format.md).
        self.wire_bytes = r.counter(
            "wire_bytes_total",
            "/predict data-plane bytes by direction (in = request "
            "bodies, out = 200 response bodies) and format "
            "(json = base64 dialect, binary = wire frames) — the "
            "wire-bytes/pair SLO signal is out+in over "
            "serve_requests_total",
            labels=("direction", "format"))
        self.wire_negotiations = r.counter(
            "wire_negotiations_total",
            "/predict format negotiations by resolved request dialect "
            "(Content-Type) and response dialect (Accept; error "
            "replies are always JSON regardless)",
            labels=("request", "response"))

    def render(self) -> str:
        return self.registry.render()


class ClusterMetrics:
    """The replicated-serving / autoscaling signal bundle
    (serve/cluster/, docs/serving.md "Cluster").

    Shared by the in-process dispatcher (mounted on the server's
    ``ServeMetrics`` registry, so one ``/metrics`` scrape covers both)
    and the front-end router (its own registry — the router process has
    no serve bundle).  The ``cluster_replicas{state=}`` gauge family and
    the per-replica queue-depth/utilization gauges are the autoscaling
    inputs: scale out when ready replicas run hot, scale in when
    utilization stays low; ``cluster_dispatch_total{outcome=}`` exposes
    failover and shed rates.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        r = registry or MetricsRegistry()
        self.registry = r
        self.replicas = r.gauge(
            "cluster_replicas",
            "engine replicas / backends by state (starting/ready/"
            "draining/drained/failed/unreachable)",
            labels=("state",))
        self.queue_depth = r.gauge(
            "cluster_queue_depth",
            "requests queued or in flight, per replica",
            labels=("replica",))
        self.dispatch = r.counter(
            "cluster_dispatch_total",
            "dispatch decisions per replica and outcome (ok/error/shed/"
            "timeout/unavailable/failover/connect_error)",
            labels=("replica", "outcome"))
        self.utilization = r.gauge(
            "cluster_utilization",
            "mean occupied fraction (0-1) of the ready replicas' batch "
            "capacity — the primary scale-out signal")
        self.session_repins = r.counter(
            "cluster_session_repins_total",
            "session frames re-pinned to a new replica, by why the old "
            "pin was unusable (failed/draining/evicted); each re-pin "
            "attempts a warm state handoff, counted separately in "
            "cluster_session_handoffs_total",
            labels=("reason",))
        self.session_handoffs = r.counter(
            "cluster_session_handoffs_total",
            "warm-start state migrations between replicas/backends by "
            "outcome: warm (state moved, next frame runs warm), "
            "cold_schema (fingerprint/version mismatch — documented cold "
            "fallback), cold_lost (no exportable state — the old home is "
            "gone or never finished a frame)",
            labels=("outcome",))
        self.autoscale_recommendation = r.gauge(
            "cluster_autoscale_recommendation",
            "recommended change in replica count from ops/autoscale.py "
            "(positive = scale out, negative = scale in, 0 = hold)")
        self.capacity_headroom = r.gauge(
            "cluster_capacity_headroom",
            "fraction of the ready fleet's fitted capacity left above "
            "the planned target_rps (1 = idle, 0 = at the fitted limit, "
            "negative = overcommitted), from the loadgen capacity model "
            "(docs/slo_harness.md); 0 when no model is configured")
        self.probe_failures = r.counter(
            "cluster_probe_failures_total",
            "health-probe failures per backend (router only)",
            labels=("replica",))
        self.router_latency = r.histogram(
            "cluster_router_hop_latency_seconds",
            "router-added latency per forwarded request (route pick + "
            "proxying, excluding the backend's own compute)")
        self.wire_stream_bytes = r.counter(
            "cluster_wire_stream_bytes_total",
            "binary /predict bytes relayed chunk-wise by the streaming "
            "forward path, by direction (in = client->backend request "
            "bodies including the peeked header+meta prefix, out = "
            "backend->client response bodies); router only "
            "(docs/wire_format.md)",
            labels=("direction",))
        self.wire_stream_peak_chunk = r.gauge(
            "cluster_wire_stream_peak_chunk_bytes",
            "largest single buffer the streaming forward path has held "
            "for any request — bounded by the 64 KiB pump window no "
            "matter the pair size, which is the router's "
            "never-buffers-a-full-body guarantee")
        self.breaker_state = r.gauge(
            "cluster_breaker_state",
            "per-backend circuit-breaker state (0 = closed, 1 = open, "
            "2 = half_open); router only (docs/fault_tolerance.md "
            "\"Circuit breaker\")",
            labels=("backend",))
        self.breaker_transitions = r.counter(
            "cluster_breaker_transitions_total",
            "circuit-breaker state transitions per backend, by the state "
            "entered (open/half_open/closed) — the counter the chaos "
            "verdict asserts on (gauges race a recovery)",
            labels=("backend", "to"))
        self.hedges = r.counter(
            "cluster_hedges_total",
            "hedged cold-request forwards by outcome: fired (a hedge was "
            "launched after the hedge delay), won (the hedge's reply was "
            "used), lost (the primary answered first; the hedge socket "
            "was abandoned)",
            labels=("outcome",))

    def set_states(self, states: Dict[str, int]) -> None:
        """Overwrite the per-state replica gauge (absent states -> 0, so
        a replica leaving a state does not leave a stale sample)."""
        for state in ("starting", "ready", "draining", "drained",
                      "failed", "unreachable"):
            self.replicas.labels(state=state).set(states.get(state, 0))

    def render(self) -> str:
        return self.registry.render()
