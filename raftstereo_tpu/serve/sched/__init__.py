"""Iteration-level continuous batching (docs/serving.md, "Scheduling").

RAFT-Stereo's anytime property makes GRU iteration count a per-request
serving knob; this package makes it a *scheduling* knob.  Instead of one
monolithic executable per request, the engine exposes the forward pass as
three phase executables (prologue / single-iteration step / epilogue,
``serve/engine.py``) and the :class:`IterationScheduler` advances one
running batch per shape bucket boundary by boundary — requests join free
slots and leave finished ones at iteration boundaries, LLM-continuous-
batching style.

* ``policy``    — pure priority/aging/deadline decisions (injected-clock
                  testable).
* ``scheduler`` — the running-batch state machine, admission control and
                  the scheduling worker thread.

Enable with ``--sched`` on ``python -m raftstereo_tpu.cli.serve``;
smoke benchmark: ``python bench.py --sched --quick``.
"""

from .policy import PRIORITIES, priority_class, should_exit  # noqa: F401
from .scheduler import IterationScheduler, SchedResult  # noqa: F401
