"""Pure scheduling policy: priority ordering with aging, and the
boundary join/leave decisions.

Everything here is a deterministic function of (request metadata, clock
reading) — no engine, no threads — which is what makes the policy unit-
testable with an injected clock (tests/test_sched.py), the same design
as the ``SessionStore``'s ``now_fn`` and the stream controller's pure
ladder walk.

Priority model: three classes, ``high`` < ``normal`` < ``low`` in
numeric class value; joins are granted in (effective class, FIFO seq)
order.  The *effective* class improves by one for every
``starvation_s`` a request has waited, so low priority is a latency
preference, never starvation: any queued request eventually out-ranks a
steady stream of fresh high-priority work.

Deadline model: ``deadline_ms`` is relative to submit.  A running
request leaves a batch early — with the anytime result it has refined
so far and ``degraded=True`` — when finishing one more boundary would
overrun its deadline (``now - t_enqueue + step_est_s > deadline_s``).
RAFT-Stereo's anytime property (accuracy rises smoothly with iteration
count; PAPERS.md, Lipson et al.) is what makes the early answer useful
rather than garbage.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["PRIORITIES", "priority_class", "effective_class",
           "queue_sort_key", "should_exit"]

# Class value by name; lower value = scheduled sooner.
PRIORITIES = ("high", "normal", "low")


def priority_class(name: str) -> int:
    """Numeric class for a priority name; raises ValueError on junk (the
    server maps that to HTTP 400)."""
    try:
        return PRIORITIES.index(name)
    except ValueError:
        raise ValueError(
            f"priority {name!r} not one of {list(PRIORITIES)}") from None


def effective_class(cls: int, waited_s: float, starvation_s: float) -> int:
    """Class after aging: one promotion per ``starvation_s`` waited,
    floored at the highest class."""
    return max(0, cls - int(waited_s // starvation_s))


def queue_sort_key(cls: int, t_enqueue: float, seq: int, now: float,
                   starvation_s: float) -> Tuple[int, int]:
    """Sort key for the join queue: (effective class, arrival seq) —
    strict priority between classes, FIFO within one."""
    return (effective_class(cls, now - t_enqueue, starvation_s), seq)


def should_exit(done_iters: int, target_iters: int, t_enqueue: float,
                deadline_s: Optional[float], now: float,
                step_est_s: float) -> Tuple[bool, bool]:
    """Boundary leave decision for one occupied slot: ``(leave, early)``.

    ``leave`` when the target is reached, or when the deadline cannot
    survive one more boundary (``early=True`` — the caller returns the
    anytime result with ``degraded=True`` meta).  Callers evaluate this
    only after a step, so ``done_iters`` is always at least one
    boundary's worth and the early answer is a real refinement."""
    if done_iters >= target_iters:
        return True, False
    if deadline_s is not None and (now - t_enqueue) + step_est_s > deadline_s:
        return True, True
    return False, False
