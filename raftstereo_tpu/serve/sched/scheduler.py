"""Iteration-level continuous batching over the engine's phase executables.

One :class:`IterationScheduler` replaces whole-request dispatch with a
per-request scheduler in the LLM-continuous-batching mold: per shape
bucket it maintains ONE running batch (``max_batch_size`` slots of
device-resident carried state), advances it one boundary at a time
through the engine's single-iteration step executable, and lets requests
join free slots and leave finished ones at iteration boundaries.  A
32-iteration request therefore never head-of-line blocks a 7-iteration
stream frame: the short job joins the same running batch at the next
boundary and leaves ~7 boundaries later, while the long job keeps
iterating in its own slot.

Engine contract (``serve/engine.py``; tests substitute stubs):

* ``bucket_of(shape) -> (h, w)`` and ``padder_of(shape)``;
* ``infer_sched_prologue(pairs, flow_inits, slots) -> (hw, state, c)``;
* ``infer_sched_join(hw, running, incoming, mask) -> (state, c)``;
* ``infer_sched_step(hw, state, iters_per_step) -> (state, c)``;
* ``infer_sched_epilogue(hw, state) -> (low, up, c)``;

and, for speculative tier cascades (serve/cascade/, requests submitted
with a ``CascadeSchedule``):

* ``infer_cascade_prologue(pairs, flow_inits, slots, cheap_mode=...,
  cert_mode=...) -> (hw, state, stage, c)``;
* ``infer_cascade_stage_join(hw, running, incoming, mask, ...) ->
  (stage, c)``;
* ``infer_cascade_handoff(hw, state, stage, slot_map, ...) ->
  (state, c)``;
* ``infer_cascade_delta(hw, prev_disp, disp, ...) -> ((B,) floats, c)``.

A cascade request drafts in the CHEAP leg's (bucket, mode) running
batch — sharing slots with that tier's plain requests — and at its
handoff boundary its slot LEAVES the cheap batch and JOINS the
certified one, carried state handed across by the handoff executable.
The handoff is a leave+join riding the existing per-(bucket, mode)
batches, not a new scheduling concept.

Correctness: per-bucket batch shape is FIXED, so joining/leaving changes
slot occupancy, not math — a request scheduled iteratively is bitwise-
identical to the same request through the monolithic executable at equal
iteration count (asserted in tests/test_sched.py).

Admission mirrors the micro-batcher it replaces: bounded queue
(``Overloaded`` beyond ``queue_limit``), per-request timeout while
queued, ``ShuttingDown`` on stop — the batcher's exception types are
reused so the HTTP layer keeps one error mapping.  Policy decisions
(priority aging, deadline early exit) are pure functions in
``policy.py`` and the clock is injectable, so the scheduling behaviour
unit-tests deterministically with no device (tests/test_sched.py).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...config import SchedConfig, ServeConfig
from ..batcher import Future, Overloaded, RequestTimedOut, ShuttingDown
from ..cascade.policy import promotion_kind, should_promote, update_ema
from .policy import (PRIORITIES, priority_class, queue_sort_key,
                     should_exit)

logger = logging.getLogger(__name__)

__all__ = ["IterationScheduler", "SchedResult"]


@dataclasses.dataclass
class SchedResult:
    """One answered request: the disparity plus how it was scheduled."""

    disparity: np.ndarray      # (H, W) float32, dataset sign convention
    disp_low: np.ndarray       # PADDED (H/f, W/f) low-res field — the
    # session state a stream forward-warps into the next frame's
    # flow_init (same contract as infer_stream_batch's middle output)
    iters: int                 # iterations actually run
    target_iters: int
    degraded: bool             # True = deadline early exit (anytime result)
    priority: str
    batch_slots: int           # occupied slots when this request left
    latency_s: float
    included_compile: bool
    # Which cluster replica answered (serve/cluster/dispatcher.py);
    # None on the single-engine path.
    replica: Optional[str] = None
    # Canonical cascade schedule string when the request ran as a
    # speculative tier cascade (serve/cascade/); None on single-tier
    # paths.  ``promoted_early`` is True when the divergence trigger
    # promoted the slot to the certified tier before its scheduled
    # handoff boundary.
    cascade: Optional[str] = None
    promoted_early: Optional[bool] = None


@dataclasses.dataclass
class _QueueItem:
    image1: np.ndarray
    image2: np.ndarray
    flow_init: Optional[np.ndarray]
    target_iters: int
    deadline_s: Optional[float]
    cls: int
    priority: str
    future: Future
    t_enqueue: float
    seq: int
    bucket: Tuple[int, int]
    trace_id: Optional[str] = None
    # Resolved precision mode of the request's accuracy tier
    # (ops/quant.py; None = the engine's default path).  Joins the
    # running-batch group: tiers never share carried state.
    mode: Optional[str] = None
    # Cascade schedule (serve/cascade.CascadeSchedule) when the request
    # runs as a speculative tier cascade; exclusive with ``mode`` (the
    # schedule's legs carry the modes).
    cascade: Optional[object] = None

    @property
    def group(self) -> Tuple:
        """Running-batch grouping key: one running batch per (bucket,
        precision mode) — slots of different tiers cannot share a state
        pytree (different dtypes AND different numerics).  A cascade
        request starts in its CHEAP leg's group, riding the same running
        batch as that tier's plain requests; the handoff moves its slot
        to the certified group."""
        if self.cascade is not None:
            return (self.bucket, self.cascade.cheap_mode)
        return (self.bucket, self.mode)


class _Slot:
    """One occupied slot of a running batch (worker-thread state)."""

    def __init__(self, item: _QueueItem, padder, compile_seen: bool):
        self.item = item
        self.padder = padder
        self.done_iters = 0
        self.compile_seen = compile_seen
        # Cascade bookkeeping (worker-thread state, like everything
        # else here): phase is "cheap" until the handoff, "certified"
        # after; ``ema`` is the divergence trigger's per-slot EMA of the
        # boundary disparity delta (serve/cascade/policy.py);
        # ``leave_at`` is the iteration count the slot exits at, set at
        # promotion (>= target_iters when the certified batch was full
        # at the scheduled boundary and the certifying leg must still
        # run whole).
        self.schedule = item.cascade          # CascadeSchedule or None
        self.phase = "cheap" if item.cascade is not None else None
        self.ema: Optional[float] = None
        self.promoted_early = False
        self.promoted_at: Optional[int] = None  # done_iters at handoff
        self.leave_at: Optional[int] = None


class _RunningBatch:
    """Per-(bucket, mode) running batch: device state + slot table
    (worker-thread state; readers go through
    ``IterationScheduler.stats``)."""

    def __init__(self, hw: Tuple[int, int], n_slots: int,
                 mode: Optional[str] = None):
        self.hw = hw
        self.mode = mode           # precision mode of every slot's state
        self.state = None          # device pytree, set at first join
        self.slots: List[Optional[_Slot]] = [None] * n_slots
        self.step_est_s = 0.0      # EMA of boundary latency (deadline est)
        # Cascade side-car: the staged certified-tier state for this
        # batch's cascade slots (engine.infer_cascade_prologue), None
        # until the first cascade join.  Lanes of non-cascade slots are
        # dead weight the handoff's slot_map never gathers.  Worker-
        # thread-confined like ``state``.
        self.cascade_stage = None

    def occupied(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    def free(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]

    def cascade_slots(self) -> List[int]:
        """Occupied slots still drafting on the cheap leg of a cascade."""
        return [i for i in self.occupied()
                if self.slots[i].phase == "cheap"]


class IterationScheduler:
    """Thread-safe request queue + single scheduling worker over an
    engine's phase executables."""

    def __init__(self, engine, config: ServeConfig, metrics=None,
                 tracer=None, now_fn=time.perf_counter):
        self.engine = engine
        self.cfg = config
        self.sched_cfg: SchedConfig = config.sched or SchedConfig()
        self.metrics = metrics
        self.tracer = tracer  # obs.Tracer or None (tracing is optional)
        self._now = now_fn    # injectable clock (policy + latency + spans)
        self._cv = threading.Condition()
        self._queue: List[_QueueItem] = []  # guarded_by: _cv
        self._seq = 0  # guarded_by: _cv
        self._closed = False  # guarded_by: _cv
        self._drain = True  # guarded_by: _cv
        # Snapshot for /healthz + /debug/vars.
        self._stats = {"active_slots": 0, "buckets": {}}  # guarded_by: _cv
        # The running batches are worker-thread-confined (only the
        # scheduling loop touches them); readers use stats().  Keyed by
        # _QueueItem.group = (bucket, precision mode).
        self._running: Dict[Tuple, _RunningBatch] = {}
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "IterationScheduler":
        assert self._thread is None, "scheduler already started"
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-sched")
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the worker.  ``drain=True`` finishes everything queued and
        running first; ``drain=False`` fails queued requests immediately
        with ``ShuttingDown`` and the worker fails running slots."""
        to_fail = []
        with self._cv:
            self._closed = True
            self._drain = drain
            if not drain:
                for it in self._queue:
                    if self.metrics is not None:
                        self.metrics.sched_queue_depth.labels(
                            priority=it.priority).add(-1)
                    to_fail.append(it.future)
                self._queue.clear()
            self._cv.notify_all()
        # Outside _cv: done-callbacks may read queue depths (see
        # batcher.Future._resolve).
        for fut in to_fail:
            fut._resolve(exc=ShuttingDown("scheduler stopped"))
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "IterationScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- admission

    @property
    def queue_depth(self) -> int:
        with self._cv:  # vs a concurrent submit/close mutating the list
            return len(self._queue)

    def active_slots(self) -> int:
        with self._cv:
            return int(self._stats["active_slots"])

    def stats(self) -> Dict[str, object]:
        """Snapshot for /healthz and /debug/vars (one round stale at
        most)."""
        with self._cv:
            by_prio = {p: 0 for p in PRIORITIES}
            for it in self._queue:
                by_prio[it.priority] += 1
            return {
                "iters_per_step": self.sched_cfg.iters_per_step,
                "queue_depth": len(self._queue),
                "queue_depth_by_priority": by_prio,
                "active_slots": self._stats["active_slots"],
                "buckets": dict(self._stats["buckets"]),
            }

    def submit(self, image1: np.ndarray, image2: np.ndarray, *,
               iters: Optional[int] = None,
               flow_init: Optional[np.ndarray] = None,
               priority: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               mode: Optional[str] = None,
               cascade=None) -> Future:
        """Enqueue one stereo pair; returns a ``Future`` resolving to a
        :class:`SchedResult`.

        ``iters`` may be ANY multiple of ``iters_per_step`` up to
        ``max_iters`` (default ``cfg.iters``) — the step executable is
        iteration-count-agnostic, so arbitrary targets cost no compile.
        ``cascade`` (a ``serve.cascade.CascadeSchedule``) runs the
        request as a speculative tier cascade; the schedule fixes the
        iteration budget, so ``iters`` must be None and ``mode`` is
        carried by the schedule's legs.  Raises ``ValueError`` on a bad
        target/priority (HTTP 400), ``Overloaded`` beyond
        ``queue_limit`` (503), ``ShuttingDown`` after stop."""
        sc = self.sched_cfg
        if cascade is not None:
            if iters is not None:
                raise ValueError(
                    f"iters is fixed by the cascade schedule {cascade} "
                    "(omit it)")
            if mode is not None:
                raise ValueError(
                    "accuracy mode is carried by the cascade schedule "
                    f"{cascade} (omit it)")
            target = cascade.total_iters
        else:
            target = int(iters) if iters is not None else self.cfg.iters
        if not 1 <= target <= sc.max_iters:
            raise ValueError(
                f"iters {target} outside [1, {sc.max_iters}]")
        if target % sc.iters_per_step:
            raise ValueError(
                f"iters {target} not divisible by iters_per_step "
                f"{sc.iters_per_step}")
        cls = priority_class(priority or "normal")
        deadline_s = None
        if deadline_ms is not None:
            deadline_s = float(deadline_ms) / 1000.0
            if deadline_s <= 0:
                raise ValueError(f"deadline_ms {deadline_ms} must be > 0")
        bucket = self.engine.bucket_of(image1.shape)
        fut = Future()
        with self._cv:
            if self._closed:
                raise ShuttingDown("scheduler stopped")
            if len(self._queue) >= self.cfg.queue_limit:
                if self.metrics is not None:
                    self.metrics.shed.inc()
                raise Overloaded(
                    f"queue full ({len(self._queue)}/"
                    f"{self.cfg.queue_limit})")
            self._seq += 1
            self._queue.append(_QueueItem(
                image1, image2, flow_init, target, deadline_s, cls,
                PRIORITIES[cls], fut, self._now(), self._seq, bucket,
                trace_id, mode, cascade))
            if self.metrics is not None:
                self.metrics.sched_queue_depth.labels(
                    priority=PRIORITIES[cls]).add(1)
            self._cv.notify_all()
        return fut

    # --------------------------------------------------------------- worker

    def _loop(self) -> None:
        while True:
            abort = False
            with self._cv:
                while (not self._closed and not self._queue
                       and not self._running):
                    self._cv.wait()
                if self._closed:
                    if not self._drain:
                        abort = True
                    elif not self._queue and not self._running:
                        return
            if abort:
                # _running is worker-private; failing its futures must
                # happen outside _cv (see batcher.Future._resolve).
                self._fail_running(ShuttingDown("scheduler stopped"))
                return
            try:
                self.run_once()
            except Exception:  # pragma: no cover - defensive
                logger.exception("scheduler round failed")

    def _fail_running(self, exc: BaseException) -> None:
        for rb in self._running.values():
            for i in rb.occupied():
                rb.slots[i].item.future._resolve(exc=exc)
        self._running.clear()

    def run_once(self) -> bool:
        """One scheduling round: admit joiners at the boundary, advance
        every running batch one boundary, release finished slots.
        Returns whether any work was done (tests drive this directly with
        an injected clock; the worker thread just loops it)."""
        now = self._now()
        joins = self._select_joins(now)
        for group, items in joins.items():
            self._join(group, items)
        did_work = bool(joins)
        for group, rb in list(self._running.items()):
            if not rb.occupied():
                del self._running[group]
                continue
            did_work = True
            self._step(rb)
            self._promote(rb)
            self._leave(rb)
            if not rb.occupied():
                del self._running[group]
        self._update_stats()
        return did_work

    # ---------------------------------------------------------- round phases

    def _select_joins(self, now: float) -> Dict[Tuple,
                                                List[_QueueItem]]:
        """Pop this boundary's joiners under the queue lock: time out
        stale requests, order the rest by (aged priority, FIFO), grant
        free slots per (bucket, mode) group."""
        sc = self.sched_cfg
        timeout_s = self.cfg.request_timeout_ms / 1000.0
        joins: Dict[Tuple, List[_QueueItem]] = {}
        timed_out: List[_QueueItem] = []
        with self._cv:
            keep: List[_QueueItem] = []
            for it in self._queue:
                if now - it.t_enqueue > timeout_s:
                    if self.metrics is not None:
                        self.metrics.timeouts.inc()
                        self.metrics.sched_queue_depth.labels(
                            priority=it.priority).add(-1)
                    timed_out.append(it)
                else:
                    keep.append(it)
            keep.sort(key=lambda it: queue_sort_key(
                it.cls, it.t_enqueue, it.seq, now,
                sc.starvation_ms / 1000.0))
            free: Dict[Tuple, int] = {}
            granted: List[_QueueItem] = []
            for it in keep:
                if it.group not in free:
                    rb = self._running.get(it.group)
                    free[it.group] = (len(rb.free()) if rb is not None
                                      else self.cfg.max_batch_size)
                if free[it.group] > 0:
                    free[it.group] -= 1
                    granted.append(it)
                    joins.setdefault(it.group, []).append(it)
            for it in granted:
                keep.remove(it)
                if self.metrics is not None:
                    self.metrics.sched_queue_depth.labels(
                        priority=it.priority).add(-1)
            self._queue = keep
        # Outside _cv: done-callbacks may read queue depths (see
        # batcher.Future._resolve).
        for it in timed_out:
            if self.tracer is not None and it.trace_id is not None:
                self.tracer.record(
                    "queue_wait", it.t_enqueue, now, it.trace_id,
                    attrs={"outcome": "timeout"})
            it.future._resolve(exc=RequestTimedOut(
                f"queued {now - it.t_enqueue:.3f}s > "
                f"{timeout_s:.3f}s limit"))
        return joins

    def _join(self, group: Tuple,
              items: List[_QueueItem]) -> None:
        """Prologue the joiners at their assigned slots and merge them
        into the group's running batch.  Plain and cascade joiners run
        as SEPARATE sub-joins: plain items go through the unmodified
        single-tier prologue executable (part of the bitwise-unchanged
        guarantee for non-cascade traffic), cascade items through the
        dual prologue that also stages the certified tier's state."""
        bucket, mode = group
        rb = self._running.get(group)
        if rb is None:
            rb = self._running[group] = _RunningBatch(
                bucket, self.cfg.max_batch_size, mode)
        slots = rb.free()[:len(items)]
        assert len(slots) == len(items), (slots, len(items))
        assigned = list(zip(items, slots))
        for is_cascade in (False, True):
            sub = [(it, sl) for it, sl in assigned
                   if (it.cascade is not None) == is_cascade]
            if sub:
                self._join_sub(rb, bucket, mode, sub)

    def _join_sub(self, rb: _RunningBatch, bucket: Tuple[int, int],
                  mode: Optional[str],
                  sub: List[Tuple[_QueueItem, int]]) -> None:
        items = [it for it, _ in sub]
        slots = [sl for _, sl in sub]
        cascading = items[0].cascade is not None
        try:
            if cascading:
                cert = items[0].cascade.cert_mode
                # Grammar v1 pins the certifying leg to fp32, so every
                # cascade in a cheap-tier group shares one cert mode.
                assert all(it.cascade.cert_mode == cert for it in items)
                hw, incoming, stage, miss = \
                    self.engine.infer_cascade_prologue(
                        [(it.image1, it.image2) for it in items],
                        [it.flow_init for it in items], slots,
                        cheap_mode=mode, cert_mode=cert)
                if rb.cascade_stage is None:
                    rb.cascade_stage = stage
                else:
                    mask = np.zeros(self.cfg.max_batch_size, bool)
                    mask[slots] = True
                    rb.cascade_stage, sj_miss = \
                        self.engine.infer_cascade_stage_join(
                            bucket, rb.cascade_stage, stage, mask,
                            cheap_mode=mode, cert_mode=cert)
                    miss = miss or sj_miss
            else:
                hw, incoming, miss = self.engine.infer_sched_prologue(
                    [(it.image1, it.image2) for it in items],
                    [it.flow_init for it in items], slots, mode=mode)
            assert hw == bucket, (hw, bucket)
            # Before the join dispatch overwrites it: the prologue's own
            # timing window, for the per-request sched_prologue spans.
            seg = getattr(self.engine, "last_segments", None)
            if rb.state is None:
                rb.state = incoming
            else:
                mask = np.zeros(self.cfg.max_batch_size, bool)
                mask[slots] = True
                rb.state, join_miss = self.engine.infer_sched_join(
                    bucket, rb.state, incoming, mask, mode=mode)
                miss = miss or join_miss
        except Exception as e:  # fail the joiners, keep the batch alive
            if self.metrics is not None:
                self.metrics.errors.inc(len(items))
            for it in items:
                it.future._resolve(exc=e)
            return
        now = self._now()
        for it, slot in sub:
            rb.slots[slot] = _Slot(it, self.engine.padder_of(
                it.image1.shape), miss)
            if self.tracer is not None and it.trace_id is not None:
                self.tracer.record(
                    "queue_wait", it.t_enqueue, now, it.trace_id,
                    attrs={"bucket": f"{bucket[0]}x{bucket[1]}",
                           "slot": slot, "priority": it.priority})
                if seg is not None:
                    self.tracer.record(
                        "sched_prologue", *seg["dispatch"], it.trace_id,
                        attrs={"compile": seg["compile"]})
        if self.metrics is not None:
            self.metrics.sched_joins.inc(len(items))

    def _step(self, rb: _RunningBatch) -> None:
        """Advance every occupied slot by one boundary."""
        ips = self.sched_cfg.iters_per_step
        # Divergence trigger (serve/cascade/policy.py): hold the low-res
        # disparity from BEFORE the boundary so the per-slot delta sees
        # this step's update.  Armed only when the trigger threshold is
        # set AND a cheap-phase cascade slot is live — plain batches
        # never touch the state's leaves, keeping the opaque-pytree
        # contract for non-cascade engines/stubs.
        threshold = self.cfg.cascade_divergence
        watch = rb.cascade_slots() if threshold > 0 else []
        prev_disp = rb.state["disp"] if watch else None
        t0 = self._now()
        try:
            rb.state, miss = self.engine.infer_sched_step(rb.hw, rb.state,
                                                          ips, mode=rb.mode)
        except Exception as e:  # fail the whole batch, drop its state
            occ = rb.occupied()
            if self.metrics is not None:
                self.metrics.errors.inc(len(occ))
            for i in occ:
                rb.slots[i].item.future._resolve(exc=e)
                rb.slots[i] = None
            rb.state = None
            return
        dt = self._now() - t0
        # EMA of compile-free boundary latency: the deadline-exit estimate.
        if not miss:
            rb.step_est_s = (dt if rb.step_est_s == 0.0
                             else 0.7 * rb.step_est_s + 0.3 * dt)
        if watch:
            try:
                deltas, d_miss = self.engine.infer_cascade_delta(
                    rb.hw, prev_disp, rb.state["disp"],
                    cheap_mode=rb.mode,
                    cert_mode=rb.slots[watch[0]].schedule.cert_mode)
                miss = miss or d_miss
                for i in watch:
                    s = rb.slots[i]
                    s.ema = update_ema(s.ema, float(deltas[i]))
            except Exception:  # trigger idles; scheduled handoff stands
                logger.exception("cascade divergence delta failed")
        if self.metrics is not None:
            self.metrics.sched_steps.inc()
            if not miss:
                self.metrics.sched_step_latency.observe(dt)
        for i in rb.occupied():
            s = rb.slots[i]
            s.done_iters += ips
            s.compile_seen = s.compile_seen or miss
            if self.metrics is not None and s.schedule is not None:
                self.metrics.cascade_iterations.labels(
                    phase=s.phase).inc(ips)
            if self.tracer is not None and s.item.trace_id is not None:
                self.tracer.record(
                    "iteration", t0, t0 + dt, s.item.trace_id,
                    attrs={"i": s.done_iters, "iters_per_step": ips,
                           "compile": miss})

    def _promote(self, rb: _RunningBatch) -> None:
        """Hand cheap-phase cascade slots whose boundary has come over to
        the certified tier: scheduled promotions at the schedule's
        cheap-leg boundary, early ones when the divergence EMA spikes
        past ``cfg.cascade_divergence``.  A promoted slot leaves this
        batch and joins the certified (bucket, mode) running batch — its
        carried state crosses tiers through the engine's handoff
        executable, then merges like any other joiner.  A full certified
        batch defers the handoff to the next boundary (``leave_at``
        still guarantees the full certifying leg runs)."""
        watch = rb.cascade_slots()
        if not watch:
            return
        threshold = self.cfg.cascade_divergence
        ready: List[Tuple[int, bool]] = []
        for i in watch:
            s = rb.slots[i]
            promote, early = should_promote(
                s.done_iters, s.schedule.cheap_iters, s.ema,
                threshold if threshold > 0 else None)
            if promote:
                ready.append((i, early))
        if not ready:
            return
        cert = rb.slots[ready[0][0]].schedule.cert_mode
        # The certified group uses the same mode normalization as the
        # server's tier resolution: the engine-default mode runs under
        # the bare (bucket, None) group, so promoted slots share the
        # default fp32 running batch with plain certified traffic.
        default = getattr(self.engine, "default_mode", None)
        tgt_group = (rb.hw, None if cert == default else cert)
        tgt = self._running.get(tgt_group)
        if tgt is None:
            tgt = self._running[tgt_group] = _RunningBatch(
                rb.hw, self.cfg.max_batch_size, tgt_group[1])
        free = tgt.free()
        if not free:
            return
        moves = list(zip([i for i, _ in ready], free))  # (src, dst)
        slot_map = np.zeros(self.cfg.max_batch_size, np.int32)
        mask = np.zeros(self.cfg.max_batch_size, bool)
        for src, dst in moves:
            slot_map[dst] = src
            mask[dst] = True
        t0 = self._now()
        try:
            handed, miss = self.engine.infer_cascade_handoff(
                rb.hw, rb.state, rb.cascade_stage, slot_map,
                cheap_mode=rb.mode, cert_mode=cert)
            if tgt.state is None:
                tgt.state = handed
            else:
                tgt.state, j_miss = self.engine.infer_sched_join(
                    rb.hw, tgt.state, handed, mask, mode=tgt.mode)
                miss = miss or j_miss
        except Exception as e:  # fail the promoters, keep both batches
            if self.metrics is not None:
                self.metrics.errors.inc(len(moves))
            for src, _ in moves:
                rb.slots[src].item.future._resolve(exc=e)
                rb.slots[src] = None
            return
        now = self._now()
        early_by_src = dict(ready)
        for src, dst in moves:
            s = rb.slots[src]
            rb.slots[src] = None
            tgt.slots[dst] = s
            s.phase = "certified"
            s.promoted_early = early_by_src[src]
            s.promoted_at = s.done_iters
            s.compile_seen = s.compile_seen or miss
            # Early promotion runs ALL remaining iterations certified; a
            # batch-full-delayed handoff still runs the whole certifying
            # leg, even past the request's nominal target.
            s.leave_at = max(s.item.target_iters,
                             s.done_iters + s.schedule.cert_iters)
            if self.metrics is not None:
                self.metrics.cascade_promotions.labels(
                    kind=promotion_kind(s.promoted_early)).inc()
            if self.tracer is not None and s.item.trace_id is not None:
                self.tracer.record(
                    "cascade_handoff", t0, now, s.item.trace_id,
                    attrs={"kind": promotion_kind(s.promoted_early),
                           "iters": s.done_iters, "compile": miss})

    def _leave(self, rb: _RunningBatch) -> None:
        """Release every slot whose target is reached or whose deadline
        cannot survive another boundary (the anytime early exit)."""
        now = self._now()
        leavers = []
        for i in rb.occupied():
            s = rb.slots[i]
            # A cascade slot's exit target is ``leave_at`` (set at its
            # handoff); while still cheap-phase it has NO finish target —
            # only the deadline takes it out, as a degraded UNcertified
            # anytime answer (a full certified batch can otherwise delay
            # the handoff past the nominal target).
            target = (s.leave_at if s.leave_at is not None
                      else s.item.target_iters)
            leave, early = should_exit(
                s.done_iters, target, s.item.t_enqueue,
                s.item.deadline_s, now, rb.step_est_s)
            if s.phase == "cheap" and not early:
                continue
            if leave:
                leavers.append((i, early))
        if not leavers:
            return
        try:
            low, up, miss = self.engine.infer_sched_epilogue(rb.hw, rb.state,
                                                             mode=rb.mode)
        except Exception as e:
            if self.metrics is not None:
                self.metrics.errors.inc(len(leavers))
            for i, _ in leavers:
                rb.slots[i].item.future._resolve(exc=e)
                rb.slots[i] = None
            return
        n_occupied = len(rb.occupied())
        seg = getattr(self.engine, "last_segments", None)
        done = self._now()
        for i, early in leavers:
            s = rb.slots[i]
            it = s.item
            # .copy() on both slices: results outlive the padded batch
            # arrays (same rationale as infer_stream_batch).
            disp = s.padder.unpad(up[i:i + 1])[0, ..., 0].copy()
            disp_low = low[i, :, :, 0].copy()
            latency = done - it.t_enqueue
            if self.tracer is not None and it.trace_id is not None and \
                    seg is not None:
                self.tracer.record(
                    "sched_epilogue", *seg["dispatch"], it.trace_id,
                    attrs={"early": early, "iters": s.done_iters})
            if self.metrics is not None:
                self.metrics.sched_leaves.inc()
                if early:
                    self.metrics.sched_early_exits.inc()
                self.metrics.responses.inc()
                self.metrics.latency.observe(latency)
                if s.schedule is not None and s.phase == "certified":
                    # A completed cascade (deadline-degraded cheap-phase
                    # exits don't count: their answer never certified).
                    self.metrics.cascade_schedules.labels(
                        schedule=s.schedule.schedule).inc()
                    if s.done_iters:
                        self.metrics.cascade_fp32_fraction.set(round(
                            (s.done_iters - (s.promoted_at or 0))
                            / s.done_iters, 4))
            it.future._resolve(value=SchedResult(
                disparity=disp, disp_low=disp_low, iters=s.done_iters,
                target_iters=it.target_iters, degraded=early,
                priority=it.priority, batch_slots=n_occupied,
                latency_s=latency,
                included_compile=s.compile_seen or miss,
                cascade=(s.schedule.schedule if s.schedule is not None
                         else None),
                promoted_early=(s.promoted_early
                                if s.schedule is not None else None)))
            rb.slots[i] = None

    def _update_stats(self) -> None:
        buckets = {}
        total = 0
        for (bucket, mode), rb in self._running.items():
            n = len(rb.occupied())
            total += n
            # Default-mode batches keep the bare "HxW" stats key (the
            # historical schema); tier batches are suffixed with their
            # precision mode.
            name = f"{bucket[0]}x{bucket[1]}"
            if mode is not None:
                name = f"{name}@{mode}"
            buckets[name] = {
                "active_slots": n,
                "occupancy": round(n / self.cfg.max_batch_size, 4),
                "step_est_ms": round(rb.step_est_s * 1e3, 3),
            }
            # Conditional key keeps the historical schema for
            # cascade-free servers (/healthz consumers).
            nc = sum(1 for i in rb.occupied()
                     if rb.slots[i].schedule is not None)
            if nc:
                buckets[name]["cascade_slots"] = nc
        with self._cv:
            self._stats = {"active_slots": total, "buckets": buckets}
        if self.metrics is not None:
            self.metrics.sched_slots_active.set(total)
            cap = max(1, len(buckets)) * self.cfg.max_batch_size
            self.metrics.sched_occupancy.set(
                round(total / cap, 4) if buckets else 0.0)
