"""Iteration-level continuous batching over the engine's phase executables.

One :class:`IterationScheduler` replaces whole-request dispatch with a
per-request scheduler in the LLM-continuous-batching mold: per shape
bucket it maintains ONE running batch (``max_batch_size`` slots of
device-resident carried state), advances it one boundary at a time
through the engine's single-iteration step executable, and lets requests
join free slots and leave finished ones at iteration boundaries.  A
32-iteration request therefore never head-of-line blocks a 7-iteration
stream frame: the short job joins the same running batch at the next
boundary and leaves ~7 boundaries later, while the long job keeps
iterating in its own slot.

Engine contract (``serve/engine.py``; tests substitute stubs):

* ``bucket_of(shape) -> (h, w)`` and ``padder_of(shape)``;
* ``infer_sched_prologue(pairs, flow_inits, slots) -> (hw, state, c)``;
* ``infer_sched_join(hw, running, incoming, mask) -> (state, c)``;
* ``infer_sched_step(hw, state, iters_per_step) -> (state, c)``;
* ``infer_sched_epilogue(hw, state) -> (low, up, c)``.

Correctness: per-bucket batch shape is FIXED, so joining/leaving changes
slot occupancy, not math — a request scheduled iteratively is bitwise-
identical to the same request through the monolithic executable at equal
iteration count (asserted in tests/test_sched.py).

Admission mirrors the micro-batcher it replaces: bounded queue
(``Overloaded`` beyond ``queue_limit``), per-request timeout while
queued, ``ShuttingDown`` on stop — the batcher's exception types are
reused so the HTTP layer keeps one error mapping.  Policy decisions
(priority aging, deadline early exit) are pure functions in
``policy.py`` and the clock is injectable, so the scheduling behaviour
unit-tests deterministically with no device (tests/test_sched.py).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...config import SchedConfig, ServeConfig
from ..batcher import Future, Overloaded, RequestTimedOut, ShuttingDown
from .policy import (PRIORITIES, priority_class, queue_sort_key,
                     should_exit)

logger = logging.getLogger(__name__)

__all__ = ["IterationScheduler", "SchedResult"]


@dataclasses.dataclass
class SchedResult:
    """One answered request: the disparity plus how it was scheduled."""

    disparity: np.ndarray      # (H, W) float32, dataset sign convention
    disp_low: np.ndarray       # PADDED (H/f, W/f) low-res field — the
    # session state a stream forward-warps into the next frame's
    # flow_init (same contract as infer_stream_batch's middle output)
    iters: int                 # iterations actually run
    target_iters: int
    degraded: bool             # True = deadline early exit (anytime result)
    priority: str
    batch_slots: int           # occupied slots when this request left
    latency_s: float
    included_compile: bool
    # Which cluster replica answered (serve/cluster/dispatcher.py);
    # None on the single-engine path.
    replica: Optional[str] = None


@dataclasses.dataclass
class _QueueItem:
    image1: np.ndarray
    image2: np.ndarray
    flow_init: Optional[np.ndarray]
    target_iters: int
    deadline_s: Optional[float]
    cls: int
    priority: str
    future: Future
    t_enqueue: float
    seq: int
    bucket: Tuple[int, int]
    trace_id: Optional[str] = None
    # Resolved precision mode of the request's accuracy tier
    # (ops/quant.py; None = the engine's default path).  Joins the
    # running-batch group: tiers never share carried state.
    mode: Optional[str] = None

    @property
    def group(self) -> Tuple:
        """Running-batch grouping key: one running batch per (bucket,
        precision mode) — slots of different tiers cannot share a state
        pytree (different dtypes AND different numerics)."""
        return (self.bucket, self.mode)


class _Slot:
    """One occupied slot of a running batch (worker-thread state)."""

    def __init__(self, item: _QueueItem, padder, compile_seen: bool):
        self.item = item
        self.padder = padder
        self.done_iters = 0
        self.compile_seen = compile_seen


class _RunningBatch:
    """Per-(bucket, mode) running batch: device state + slot table
    (worker-thread state; readers go through
    ``IterationScheduler.stats``)."""

    def __init__(self, hw: Tuple[int, int], n_slots: int,
                 mode: Optional[str] = None):
        self.hw = hw
        self.mode = mode           # precision mode of every slot's state
        self.state = None          # device pytree, set at first join
        self.slots: List[Optional[_Slot]] = [None] * n_slots
        self.step_est_s = 0.0      # EMA of boundary latency (deadline est)

    def occupied(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is not None]

    def free(self) -> List[int]:
        return [i for i, s in enumerate(self.slots) if s is None]


class IterationScheduler:
    """Thread-safe request queue + single scheduling worker over an
    engine's phase executables."""

    def __init__(self, engine, config: ServeConfig, metrics=None,
                 tracer=None, now_fn=time.perf_counter):
        self.engine = engine
        self.cfg = config
        self.sched_cfg: SchedConfig = config.sched or SchedConfig()
        self.metrics = metrics
        self.tracer = tracer  # obs.Tracer or None (tracing is optional)
        self._now = now_fn    # injectable clock (policy + latency + spans)
        self._cv = threading.Condition()
        self._queue: List[_QueueItem] = []  # guarded_by: _cv
        self._seq = 0  # guarded_by: _cv
        self._closed = False  # guarded_by: _cv
        self._drain = True  # guarded_by: _cv
        # Snapshot for /healthz + /debug/vars.
        self._stats = {"active_slots": 0, "buckets": {}}  # guarded_by: _cv
        # The running batches are worker-thread-confined (only the
        # scheduling loop touches them); readers use stats().  Keyed by
        # _QueueItem.group = (bucket, precision mode).
        self._running: Dict[Tuple, _RunningBatch] = {}
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "IterationScheduler":
        assert self._thread is None, "scheduler already started"
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-sched")
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop the worker.  ``drain=True`` finishes everything queued and
        running first; ``drain=False`` fails queued requests immediately
        with ``ShuttingDown`` and the worker fails running slots."""
        to_fail = []
        with self._cv:
            self._closed = True
            self._drain = drain
            if not drain:
                for it in self._queue:
                    if self.metrics is not None:
                        self.metrics.sched_queue_depth.labels(
                            priority=it.priority).add(-1)
                    to_fail.append(it.future)
                self._queue.clear()
            self._cv.notify_all()
        # Outside _cv: done-callbacks may read queue depths (see
        # batcher.Future._resolve).
        for fut in to_fail:
            fut._resolve(exc=ShuttingDown("scheduler stopped"))
        if self._thread is not None:
            self._thread.join(timeout)

    def __enter__(self) -> "IterationScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- admission

    @property
    def queue_depth(self) -> int:
        with self._cv:  # vs a concurrent submit/close mutating the list
            return len(self._queue)

    def active_slots(self) -> int:
        with self._cv:
            return int(self._stats["active_slots"])

    def stats(self) -> Dict[str, object]:
        """Snapshot for /healthz and /debug/vars (one round stale at
        most)."""
        with self._cv:
            by_prio = {p: 0 for p in PRIORITIES}
            for it in self._queue:
                by_prio[it.priority] += 1
            return {
                "iters_per_step": self.sched_cfg.iters_per_step,
                "queue_depth": len(self._queue),
                "queue_depth_by_priority": by_prio,
                "active_slots": self._stats["active_slots"],
                "buckets": dict(self._stats["buckets"]),
            }

    def submit(self, image1: np.ndarray, image2: np.ndarray, *,
               iters: Optional[int] = None,
               flow_init: Optional[np.ndarray] = None,
               priority: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               mode: Optional[str] = None) -> Future:
        """Enqueue one stereo pair; returns a ``Future`` resolving to a
        :class:`SchedResult`.

        ``iters`` may be ANY multiple of ``iters_per_step`` up to
        ``max_iters`` (default ``cfg.iters``) — the step executable is
        iteration-count-agnostic, so arbitrary targets cost no compile.
        Raises ``ValueError`` on a bad target/priority (HTTP 400),
        ``Overloaded`` beyond ``queue_limit`` (503), ``ShuttingDown``
        after stop."""
        sc = self.sched_cfg
        target = int(iters) if iters is not None else self.cfg.iters
        if not 1 <= target <= sc.max_iters:
            raise ValueError(
                f"iters {target} outside [1, {sc.max_iters}]")
        if target % sc.iters_per_step:
            raise ValueError(
                f"iters {target} not divisible by iters_per_step "
                f"{sc.iters_per_step}")
        cls = priority_class(priority or "normal")
        deadline_s = None
        if deadline_ms is not None:
            deadline_s = float(deadline_ms) / 1000.0
            if deadline_s <= 0:
                raise ValueError(f"deadline_ms {deadline_ms} must be > 0")
        bucket = self.engine.bucket_of(image1.shape)
        fut = Future()
        with self._cv:
            if self._closed:
                raise ShuttingDown("scheduler stopped")
            if len(self._queue) >= self.cfg.queue_limit:
                if self.metrics is not None:
                    self.metrics.shed.inc()
                raise Overloaded(
                    f"queue full ({len(self._queue)}/"
                    f"{self.cfg.queue_limit})")
            self._seq += 1
            self._queue.append(_QueueItem(
                image1, image2, flow_init, target, deadline_s, cls,
                PRIORITIES[cls], fut, self._now(), self._seq, bucket,
                trace_id, mode))
            if self.metrics is not None:
                self.metrics.sched_queue_depth.labels(
                    priority=PRIORITIES[cls]).add(1)
            self._cv.notify_all()
        return fut

    # --------------------------------------------------------------- worker

    def _loop(self) -> None:
        while True:
            abort = False
            with self._cv:
                while (not self._closed and not self._queue
                       and not self._running):
                    self._cv.wait()
                if self._closed:
                    if not self._drain:
                        abort = True
                    elif not self._queue and not self._running:
                        return
            if abort:
                # _running is worker-private; failing its futures must
                # happen outside _cv (see batcher.Future._resolve).
                self._fail_running(ShuttingDown("scheduler stopped"))
                return
            try:
                self.run_once()
            except Exception:  # pragma: no cover - defensive
                logger.exception("scheduler round failed")

    def _fail_running(self, exc: BaseException) -> None:
        for rb in self._running.values():
            for i in rb.occupied():
                rb.slots[i].item.future._resolve(exc=exc)
        self._running.clear()

    def run_once(self) -> bool:
        """One scheduling round: admit joiners at the boundary, advance
        every running batch one boundary, release finished slots.
        Returns whether any work was done (tests drive this directly with
        an injected clock; the worker thread just loops it)."""
        now = self._now()
        joins = self._select_joins(now)
        for group, items in joins.items():
            self._join(group, items)
        did_work = bool(joins)
        for group, rb in list(self._running.items()):
            if not rb.occupied():
                del self._running[group]
                continue
            did_work = True
            self._step(rb)
            self._leave(rb)
            if not rb.occupied():
                del self._running[group]
        self._update_stats()
        return did_work

    # ---------------------------------------------------------- round phases

    def _select_joins(self, now: float) -> Dict[Tuple,
                                                List[_QueueItem]]:
        """Pop this boundary's joiners under the queue lock: time out
        stale requests, order the rest by (aged priority, FIFO), grant
        free slots per (bucket, mode) group."""
        sc = self.sched_cfg
        timeout_s = self.cfg.request_timeout_ms / 1000.0
        joins: Dict[Tuple, List[_QueueItem]] = {}
        timed_out: List[_QueueItem] = []
        with self._cv:
            keep: List[_QueueItem] = []
            for it in self._queue:
                if now - it.t_enqueue > timeout_s:
                    if self.metrics is not None:
                        self.metrics.timeouts.inc()
                        self.metrics.sched_queue_depth.labels(
                            priority=it.priority).add(-1)
                    timed_out.append(it)
                else:
                    keep.append(it)
            keep.sort(key=lambda it: queue_sort_key(
                it.cls, it.t_enqueue, it.seq, now,
                sc.starvation_ms / 1000.0))
            free: Dict[Tuple, int] = {}
            granted: List[_QueueItem] = []
            for it in keep:
                if it.group not in free:
                    rb = self._running.get(it.group)
                    free[it.group] = (len(rb.free()) if rb is not None
                                      else self.cfg.max_batch_size)
                if free[it.group] > 0:
                    free[it.group] -= 1
                    granted.append(it)
                    joins.setdefault(it.group, []).append(it)
            for it in granted:
                keep.remove(it)
                if self.metrics is not None:
                    self.metrics.sched_queue_depth.labels(
                        priority=it.priority).add(-1)
            self._queue = keep
        # Outside _cv: done-callbacks may read queue depths (see
        # batcher.Future._resolve).
        for it in timed_out:
            if self.tracer is not None and it.trace_id is not None:
                self.tracer.record(
                    "queue_wait", it.t_enqueue, now, it.trace_id,
                    attrs={"outcome": "timeout"})
            it.future._resolve(exc=RequestTimedOut(
                f"queued {now - it.t_enqueue:.3f}s > "
                f"{timeout_s:.3f}s limit"))
        return joins

    def _join(self, group: Tuple,
              items: List[_QueueItem]) -> None:
        """Prologue the joiners at their assigned slots and merge them
        into the group's running batch."""
        bucket, mode = group
        rb = self._running.get(group)
        if rb is None:
            rb = self._running[group] = _RunningBatch(
                bucket, self.cfg.max_batch_size, mode)
        slots = rb.free()[:len(items)]
        assert len(slots) == len(items), (slots, len(items))
        try:
            hw, incoming, miss = self.engine.infer_sched_prologue(
                [(it.image1, it.image2) for it in items],
                [it.flow_init for it in items], slots, mode=mode)
            assert hw == bucket, (hw, bucket)
            # Before the join dispatch overwrites it: the prologue's own
            # timing window, for the per-request sched_prologue spans.
            seg = getattr(self.engine, "last_segments", None)
            if rb.state is None:
                rb.state = incoming
            else:
                mask = np.zeros(self.cfg.max_batch_size, bool)
                mask[slots] = True
                rb.state, join_miss = self.engine.infer_sched_join(
                    bucket, rb.state, incoming, mask, mode=mode)
                miss = miss or join_miss
        except Exception as e:  # fail the joiners, keep the batch alive
            if self.metrics is not None:
                self.metrics.errors.inc(len(items))
            for it in items:
                it.future._resolve(exc=e)
            return
        now = self._now()
        for it, slot in zip(items, slots):
            rb.slots[slot] = _Slot(it, self.engine.padder_of(
                it.image1.shape), miss)
            if self.tracer is not None and it.trace_id is not None:
                self.tracer.record(
                    "queue_wait", it.t_enqueue, now, it.trace_id,
                    attrs={"bucket": f"{bucket[0]}x{bucket[1]}",
                           "slot": slot, "priority": it.priority})
                if seg is not None:
                    self.tracer.record(
                        "sched_prologue", *seg["dispatch"], it.trace_id,
                        attrs={"compile": seg["compile"]})
        if self.metrics is not None:
            self.metrics.sched_joins.inc(len(items))

    def _step(self, rb: _RunningBatch) -> None:
        """Advance every occupied slot by one boundary."""
        ips = self.sched_cfg.iters_per_step
        t0 = self._now()
        try:
            rb.state, miss = self.engine.infer_sched_step(rb.hw, rb.state,
                                                          ips, mode=rb.mode)
        except Exception as e:  # fail the whole batch, drop its state
            occ = rb.occupied()
            if self.metrics is not None:
                self.metrics.errors.inc(len(occ))
            for i in occ:
                rb.slots[i].item.future._resolve(exc=e)
                rb.slots[i] = None
            rb.state = None
            return
        dt = self._now() - t0
        # EMA of compile-free boundary latency: the deadline-exit estimate.
        if not miss:
            rb.step_est_s = (dt if rb.step_est_s == 0.0
                             else 0.7 * rb.step_est_s + 0.3 * dt)
        if self.metrics is not None:
            self.metrics.sched_steps.inc()
            if not miss:
                self.metrics.sched_step_latency.observe(dt)
        for i in rb.occupied():
            s = rb.slots[i]
            s.done_iters += ips
            s.compile_seen = s.compile_seen or miss
            if self.tracer is not None and s.item.trace_id is not None:
                self.tracer.record(
                    "iteration", t0, t0 + dt, s.item.trace_id,
                    attrs={"i": s.done_iters, "iters_per_step": ips,
                           "compile": miss})

    def _leave(self, rb: _RunningBatch) -> None:
        """Release every slot whose target is reached or whose deadline
        cannot survive another boundary (the anytime early exit)."""
        now = self._now()
        leavers = []
        for i in rb.occupied():
            s = rb.slots[i]
            leave, early = should_exit(
                s.done_iters, s.item.target_iters, s.item.t_enqueue,
                s.item.deadline_s, now, rb.step_est_s)
            if leave:
                leavers.append((i, early))
        if not leavers:
            return
        try:
            low, up, miss = self.engine.infer_sched_epilogue(rb.hw, rb.state,
                                                             mode=rb.mode)
        except Exception as e:
            if self.metrics is not None:
                self.metrics.errors.inc(len(leavers))
            for i, _ in leavers:
                rb.slots[i].item.future._resolve(exc=e)
                rb.slots[i] = None
            return
        n_occupied = len(rb.occupied())
        seg = getattr(self.engine, "last_segments", None)
        done = self._now()
        for i, early in leavers:
            s = rb.slots[i]
            it = s.item
            # .copy() on both slices: results outlive the padded batch
            # arrays (same rationale as infer_stream_batch).
            disp = s.padder.unpad(up[i:i + 1])[0, ..., 0].copy()
            disp_low = low[i, :, :, 0].copy()
            latency = done - it.t_enqueue
            if self.tracer is not None and it.trace_id is not None and \
                    seg is not None:
                self.tracer.record(
                    "sched_epilogue", *seg["dispatch"], it.trace_id,
                    attrs={"early": early, "iters": s.done_iters})
            if self.metrics is not None:
                self.metrics.sched_leaves.inc()
                if early:
                    self.metrics.sched_early_exits.inc()
                self.metrics.responses.inc()
                self.metrics.latency.observe(latency)
            it.future._resolve(value=SchedResult(
                disparity=disp, disp_low=disp_low, iters=s.done_iters,
                target_iters=it.target_iters, degraded=early,
                priority=it.priority, batch_slots=n_occupied,
                latency_s=latency,
                included_compile=s.compile_seen or miss))
            rb.slots[i] = None

    def _update_stats(self) -> None:
        buckets = {}
        total = 0
        for (bucket, mode), rb in self._running.items():
            n = len(rb.occupied())
            total += n
            # Default-mode batches keep the bare "HxW" stats key (the
            # historical schema); tier batches are suffixed with their
            # precision mode.
            name = f"{bucket[0]}x{bucket[1]}"
            if mode is not None:
                name = f"{name}@{mode}"
            buckets[name] = {
                "active_slots": n,
                "occupancy": round(n / self.cfg.max_batch_size, 4),
                "step_est_ms": round(rb.step_est_s * 1e3, 3),
            }
        with self._cv:
            self._stats = {"active_slots": total, "buckets": buckets}
        if self.metrics is not None:
            self.metrics.sched_slots_active.set(total)
            cap = max(1, len(buckets)) * self.cfg.max_batch_size
            self.metrics.sched_occupancy.set(
                round(total / cap, 4) if buckets else 0.0)
