"""Replicated multi-chip serving (docs/serving.md "Cluster").

Two composition levels over the single-engine serving stack:

* **in one process** — :class:`ReplicaSet` instantiates N independent
  ``BatchEngine`` stacks (one per device from
  ``parallel.mesh.replica_devices``; virtual CPU devices in tier-1) and
  :class:`ClusterDispatcher` is the single admission surface over them:
  least-outstanding-work placement for cold requests, session-sticky
  routing for stream/scheduled work.  Enabled by
  ``ServeConfig.cluster`` (``cli.serve --replicas N``);
* **across processes/hosts** — :class:`StereoRouter`
  (``python -m raftstereo_tpu.cli.router``) fronts N backend
  ``StereoServer``s with /healthz-driven readiness gating, bounded
  retry-with-backoff failover of idempotent cold requests, session
  pinning, and explicit per-backend drain.

Both levels export the same ``cluster_*`` autoscaling metric families
(serve/metrics.ClusterMetrics) and record their hops in the shared
trace pipeline (obs/).
"""

import importlib

# Lazy (PEP 562) exports, same rationale as serve/__init__: the router
# members are model-free (stdlib + metrics/obs only) while replica/
# dispatcher pull the full engine stack — a ``cli.router`` process must
# be able to reach ``build_router`` without importing jax/flax/models.
_EXPORTS = {
    "Backend": ".router",
    "StereoRouter": ".router",
    "build_router": ".router",
    "ClusterDispatcher": ".dispatcher",
    "Replica": ".replica",
    "ReplicaSet": ".replica",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        rel = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    value = getattr(importlib.import_module(rel, __name__), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
