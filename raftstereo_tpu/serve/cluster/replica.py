"""Engine replicas: N independent serving stacks, one per device.

A :class:`Replica` is the full single-engine dispatch stack of PR 1/7 —
``BatchEngine`` plus exactly one of ``DynamicBatcher`` (monolithic) or
``IterationScheduler`` (``--sched``), plus a ``StreamRunner`` when
streaming is enabled — pinned to one device.  A :class:`ReplicaSet`
instantiates one per device from ``parallel.mesh.replica_devices``, so
replica layout follows the same device order training's data-parallel
axis uses; on the CPU host platform the devices are the virtual ones
``--xla_force_host_platform_device_count`` fans out, which is how the
tier-1 tests run a real multi-replica cluster without a pod.

Key properties:

* every replica owns its OWN jit wrappers and compile cache — replicas
  warm independently (in parallel) and never serialize on one another's
  dispatch lock;
* warmup is in-process ladder warmup only: each replica compiles its
  configured buckets before it is marked ``ready`` (the persistent JAX
  compile cache is broken on this container — see CHANGES.md PR 2 — so
  replicas never share serialized executables);
* per-replica results are bitwise-identical to the single-engine path:
  the executables are the same programs at the same shapes, just placed
  on different devices (asserted in tests/test_cluster.py).

Replica states: ``starting`` (warming, unroutable) -> ``ready`` ->
``draining`` (finishing admitted work) -> ``drained``; ``failed`` after
``fail_threshold`` consecutive engine errors (stops receiving new work;
the dispatcher reports it in ``cluster_replicas{state="failed"}``).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from ...config import ClusterConfig, ServeConfig
from ..batcher import DynamicBatcher
from ..engine import BatchEngine
from ..metrics import Gauge, LabelFamily, ServeMetrics
from ..sched import IterationScheduler

logger = logging.getLogger(__name__)

__all__ = ["Replica", "ReplicaSet"]

_STATES = ("starting", "ready", "draining", "drained", "failed")


class _ReplicaMetricsView:
    """Per-replica facade over the shared ``ServeMetrics``.

    Counters and histograms pass through — they are additive, so N
    replica workers incrementing one shared family is exactly the
    cluster-wide total.  The scalar ``.set()`` gauges are NOT additive:
    each replica's batcher/scheduler writes its own absolute value, so
    sharing one sample is last-writer-wins noise (replica r1 setting
    ``serve_queue_depth 0`` right after r0 set 10).  Those gauges are
    replaced with private unregistered instruments here, and the
    dispatcher re-exports cluster-wide aggregates onto the shared
    (rendered) ones in ``_refresh_gauges``."""

    def __init__(self, shared: ServeMetrics):
        self._shared = shared
        self.queue_depth = Gauge()
        self.sched_slots_active = Gauge()
        self.sched_occupancy = Gauge()
        self.sched_queue_depth = LabelFamily(Gauge, ("priority",))

    def __getattr__(self, name):
        return getattr(self._shared, name)


class Replica:
    """One device's serving stack plus its routing state."""

    def __init__(self, rid: int, device, model, variables,
                 config: ServeConfig, metrics: ServeMetrics,
                 tracer=None, fail_threshold: int = 3,
                 fault_plan=None):
        self.rid = rid
        self.name = f"r{rid}"
        self.device = device
        self.cfg = config
        self._fail_threshold = fail_threshold
        # Scalar gauges are private per replica (see _ReplicaMetricsView);
        # the dispatcher aggregates them back onto the shared registry.
        self.metrics = _ReplicaMetricsView(metrics)
        # fault_plan is the PROCESS-shared chaos plan (utils/faults.py):
        # a slow_replica budget armed over /debug/faults reaches every
        # replica's dispatch seam, and each consumed firing is counted
        # once process-wide.
        self.engine = BatchEngine(model, variables, config, self.metrics,
                                  device=device, fault_plan=fault_plan)
        self.scheduler: Optional[IterationScheduler] = None
        self.batcher: Optional[DynamicBatcher] = None
        if config.sched is not None:
            self.scheduler = IterationScheduler(
                self.engine, config, self.metrics, tracer=tracer).start()
        else:
            self.batcher = DynamicBatcher(
                self.engine, config, self.metrics, tracer=tracer).start()
        self.stream = None
        if config.stream is not None:
            from ...stream.runner import StreamRunner  # local: avoids an
            # import cycle (stream.runner's engine builder imports serve)
            self.stream = StreamRunner(self.engine, config.stream,
                                       self.metrics, tracer=tracer,
                                       scheduler=self.scheduler)
        self._lock = threading.Lock()
        self._state = "starting"  # guarded_by: _lock
        self._inflight = 0  # guarded_by: _lock
        self._consecutive_errors = 0  # guarded_by: _lock

    # -------------------------------------------------------------- state

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:  # guarded_by: _lock
        """``draining`` resolves to ``drained`` once the last admitted
        request has been answered (queue empty + nothing in flight)."""
        if self._state == "draining" and self._inflight == 0 \
                and self._backend_depth() == 0:
            return "drained"
        return self._state

    def _backend_depth(self) -> int:
        if self.scheduler is not None:
            return self.scheduler.queue_depth + self.scheduler.active_slots()
        return self.batcher.queue_depth

    def outstanding(self) -> int:
        """Work placed on this replica and not yet answered — the
        least-outstanding-work placement signal."""
        with self._lock:
            inflight = self._inflight
        return inflight + self._backend_depth()

    def routable(self) -> bool:
        with self._lock:
            return self._state == "ready"

    def mark_ready(self) -> None:
        with self._lock:
            if self._state == "starting":
                self._state = "ready"

    def mark_failed(self, why: str) -> None:
        with self._lock:
            if self._state != "failed":
                logger.error("replica %s marked failed: %s", self.name, why)
                self._state = "failed"

    def drain(self) -> None:
        """Stop admitting; already-admitted work keeps running to
        completion (the batcher/scheduler worker is not stopped)."""
        with self._lock:
            if self._state in ("starting", "ready"):
                self._state = "draining"

    # ----------------------------------------------------------- dispatch

    def begin_dispatch(self) -> None:
        with self._lock:
            self._inflight += 1

    def end_dispatch(self, ok: bool) -> None:
        """Settle one dispatch.  ``ok`` means the engine worked —
        answered, shed, or timed out; only engine FAILURES count toward
        ``fail_threshold`` (an overloaded replica is healthy)."""
        with self._lock:
            self._inflight -= 1
            if ok:
                self._consecutive_errors = 0
            else:
                self._consecutive_errors += 1
                if self._consecutive_errors >= self._fail_threshold \
                        and self._state != "failed":
                    logger.error(
                        "replica %s: %d consecutive engine errors, "
                        "marking failed", self.name,
                        self._consecutive_errors)
                    self._state = "failed"

    # ---------------------------------------------------------- lifecycle

    def warmup(self, modes=None) -> None:
        """In-process ladder warmup, mirroring ``build_server``: compile
        every configured bucket (and sched phases / stream ladder levels
        / advertised accuracy-tier modes) on THIS replica's device, then
        become routable."""
        try:
            if self.cfg.sched is not None:
                if self.cfg.warmup:
                    self.engine.warmup_sched(
                        iters_per_step=self.cfg.sched.iters_per_step,
                        modes=modes)
            else:
                if self.cfg.warmup:
                    self.engine.warmup(modes=modes)
                if self.cfg.stream is not None and self.cfg.stream_warmup:
                    self.engine.warmup_stream(ladder=self.cfg.stream.ladder,
                                              modes=modes)
        except Exception as e:
            self.mark_failed(f"warmup failed: {e}")
            raise
        self.mark_ready()

    def stop(self, drain: bool = True) -> None:
        if self.batcher is not None:
            self.batcher.stop(drain=drain)
        if self.scheduler is not None:
            self.scheduler.stop(drain=drain)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            state = self._effective_state()
            inflight = self._inflight
        info: Dict[str, object] = {
            "state": state,
            "device": str(self.device),
            "inflight": inflight,
            "queue_depth": self._backend_depth(),
            "compiled": self.engine.cache_stats["compiled"],
        }
        if self.stream is not None:
            info["sessions"] = len(self.stream.store)
        return info


class ReplicaSet:
    """N replicas over the mesh's replica devices, warmed concurrently.

    The set itself is mostly bookkeeping: replicas are independent by
    construction, and all routing policy lives in the dispatcher."""

    def __init__(self, model, variables, config: ServeConfig,
                 metrics: Optional[ServeMetrics] = None, tracer=None,
                 devices=None, fault_plan=None):
        from ...parallel.mesh import replica_devices

        self.cfg = config
        self.cluster_cfg: ClusterConfig = config.cluster or ClusterConfig()
        self.metrics = metrics or ServeMetrics()
        if devices is None:
            devices = replica_devices(self.cluster_cfg.replicas)
        self.replicas: List[Replica] = [
            Replica(i, dev, model, variables, config, self.metrics,
                    tracer=tracer,
                    fail_threshold=self.cluster_cfg.fail_threshold,
                    fault_plan=fault_plan)
            for i, dev in enumerate(devices)]

    def __len__(self) -> int:
        return len(self.replicas)

    @property
    def engine(self) -> BatchEngine:
        """Shape/warmth policy view — what the HTTP layer's admission
        checks use.  Bucketing is pure config (identical across
        replicas) but warmth is per-replica compile state, so prefer a
        READY replica's engine: if replica 0's warmup failed while
        others warmed (the set tolerates that), its cold cache must not
        make admission reject traffic the ready replicas can serve."""
        ready = self.ready_replicas()
        return (ready[0] if ready else self.replicas[0]).engine

    def ready_replicas(self) -> List[Replica]:
        return [r for r in self.replicas if r.routable()]

    def states(self) -> Dict[str, int]:
        counts = {s: 0 for s in _STATES}
        for r in self.replicas:
            counts[r.state] += 1
        return counts

    def warmup(self, modes=None) -> None:
        """Warm every replica; parallel by default (each engine owns its
        own lock and compile cache, so the warmups are independent).  A
        replica whose warmup fails is marked ``failed`` and skipped —
        the set is usable as long as one replica became ready.
        ``modes`` (precision modes incl. advertised accuracy tiers,
        build_server) is forwarded to every replica so tier warmth is
        cluster-uniform."""
        if not self.cluster_cfg.warmup_parallel:
            for r in self.replicas:
                try:
                    r.warmup(modes=modes)
                except Exception:
                    logger.exception("replica %s warmup failed", r.name)
            self._require_ready()
            return
        threads = [threading.Thread(target=self._warm_one, args=(r, modes),
                                    name=f"warmup-{r.name}", daemon=True)
                   for r in self.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._require_ready()

    def _warm_one(self, replica: Replica, modes=None) -> None:
        try:
            replica.warmup(modes=modes)
        except Exception:  # already marked failed; keep the others going
            logger.exception("replica %s warmup failed", replica.name)

    def _require_ready(self) -> None:
        if not self.ready_replicas():
            raise RuntimeError(
                "no replica finished warmup; cluster cannot serve "
                f"(states: {self.states()})")

    def stop(self, drain: bool = True) -> None:
        for r in self.replicas:
            r.stop(drain=drain)

    def stats(self) -> Dict[str, object]:
        return {"replicas": {r.name: r.stats() for r in self.replicas},
                "states": self.states()}
