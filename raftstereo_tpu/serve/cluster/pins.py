"""Session -> target pin table, shared by the in-process dispatcher and
the HTTP router (stdlib-only: the router never imports the engine/model
stack).

One implementation for one policy: sessions are sticky because RAFT's
warm-start state lives next to one engine's compile cache, so both
placement layers need the same LRU-bounded get-or-assign — an evicted or
re-pinned session's next frame runs cold only when the warm handoff
(dispatcher/router migration, PR 13) cannot move its state, never errors.
The whole decision (read pin, validate it, choose a replacement, write,
evict) happens under ONE lock acquisition: two concurrent first frames of
a session must agree on the pin, not race to different targets.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, List, Optional, Tuple

__all__ = ["PinTable"]


class PinTable:
    """LRU-bounded ``session_id -> target id`` map with atomic
    get-or-assign."""

    def __init__(self, limit: int):
        assert limit >= 1, limit
        self.limit = limit
        self._lock = threading.Lock()
        self._pins = collections.OrderedDict()  # guarded_by: _lock

    def __len__(self) -> int:
        with self._lock:
            return len(self._pins)

    def pin(self, session_id: str,
            still_ok: Callable[[int], bool],
            choose: Callable[[], Optional[int]]
            ) -> Tuple[Optional[int], bool, Optional[int]]:
        """Sticky target for ``session_id``: the existing pin if
        ``still_ok(target)``, else ``choose()`` (called under the table
        lock — keep it cheap and never have it take this table's lock).

        Returns ``(target, repinned, old)``; ``(None, False, old)`` when
        the pin is stale/absent and ``choose()`` found no target (the pin
        is left untouched).  ``repinned`` is True only when a LIVE pin
        was replaced — the caller counts it and attempts the warm
        handoff from ``old`` (which is where the session's state still
        lives) to ``target``."""
        with self._lock:
            old = self._pins.get(session_id)
            if old is not None and still_ok(old):
                self._pins.move_to_end(session_id)
                return old, False, old
            new = choose()
            if new is None:
                return None, False, old
            self._pins[session_id] = new
            self._pins.move_to_end(session_id)
            while len(self._pins) > self.limit:
                self._pins.popitem(last=False)
            return new, old is not None, old

    def peek(self, session_id: str) -> Optional[int]:
        """Current pin without touching LRU order (None if absent)."""
        with self._lock:
            return self._pins.get(session_id)

    def pinned_to(self, target: int) -> List[str]:
        """All session ids currently pinned to ``target``, LRU order —
        the drain-time migration worklist."""
        with self._lock:
            return [s for s, t in self._pins.items() if t == target]

    def reassign(self, session_id: str, expect: Optional[int],
                 new: int) -> bool:
        """Compare-and-swap the pin to ``new`` iff it still reads
        ``expect`` (``None`` = absent).  False means a concurrent
        ``pin()`` already moved it — the migration loop must not clobber
        that fresher decision."""
        with self._lock:
            if self._pins.get(session_id) != expect:
                return False
            self._pins[session_id] = new
            self._pins.move_to_end(session_id)
            while len(self._pins) > self.limit:
                self._pins.popitem(last=False)
            return True
