"""Front-end HTTP router over N backend stereo servers.

The router is model-free and holds no device state: it proxies
``/predict`` bodies byte-for-byte to one of N ``StereoServer`` backends
(possibly on other hosts), choosing the backend the way the in-process
dispatcher chooses a replica:

* **readiness gating** — a background prober polls every backend's
  ``/healthz`` (``live``/``ready``/``draining``); only ``ready``
  backends are routable, so a restarting backend is never routed to
  while it pays its warmup compiles;
* **least outstanding work** — cold requests go to the ready backend
  with the fewest (router-side in-flight + last-probed queue) requests;
* **session stickiness + warm migration** — frames of one session pin
  to one backend (warm-start state is backend-local); when a backend is
  lost or draining the router MIGRATES the session instead of merely
  re-pinning it: state is pulled over ``GET /debug/sessions/<id>`` and
  pushed to the new home over ``POST /debug/sessions`` (raw-bytes
  bitwise snapshot, serve/server.py), so any backend can resume any
  stream.  ``cluster_session_repins_total{reason=}`` says why the pin
  moved, ``cluster_session_handoffs_total{outcome=}`` whether the
  warmth survived (warm / cold_schema / cold_lost);
* **bounded failover** — cold inference is idempotent (a pure function
  of the images), so a backend failure mid-request retries on another
  backend with exponential backoff + jitter, up to ``retries`` extra
  attempts.  Session frames are NOT idempotent (a duplicate would
  advance the session), so they only retry connect-phase failures
  (request provably never reached a backend) and otherwise fail with a
  clean 503 — never a hang: every socket the router opens has a
  timeout.

``POST /debug/drain`` with ``{"backend": "b0"}`` takes a backend out of
rotation and forwards the drain: the backend stops admitting, finishes
running batches, and reports ``drained`` on its /healthz, which the
router's prober (and ``GET /healthz`` here) surfaces.

``POST /debug/restart`` with ``{"backend": "b0"}`` is the zero-downtime
rolling-restart verb (docs/serving.md "Session migration & rolling
restart"): drain -> wait for the backend's in-flight work to finish ->
migrate every pinned session warm to the remaining backends -> reply;
the operator then restarts/upgrades the process with ``warmup_async``
and the readiness probe gates its rejoin — no frame of a migrated
session ever runs cold.

The ``cluster_*`` metric families on ``GET /metrics`` are the
autoscaling signals (docs/serving.md "Cluster"); ``ops/autoscale.py``
consumes them here and surfaces scale advice in ``GET /debug/vars`` and
the ``cluster_autoscale_recommendation`` gauge.
"""

from __future__ import annotations

import http.client
import json
import logging
import queue
import socket
import threading
import time
import zlib
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, quote, urlparse

from ... import wire
from ...config import RouterConfig
from ...obs import (
    AlertClass,
    BurnRateAlerts,
    FleetFederator,
    TailSampler,
    Tracer,
    build_info,
    dump_threads,
    stitch_sources,
    trace_response,
)
from ...ops.autoscale import Autoscaler, load_capacity_model
from ...utils.backoff import backoff_delay
from ...utils.faults import FaultPlan
from ...utils.profiling import LatencyHistogram
from ..httpbase import (
    TRACE_HEADER,
    WIRE_CHUNK,
    JsonRequestHandler,
    format_trace_context,
)
from ..metrics import ClusterMetrics, MetricsRegistry
from .pins import PinTable

logger = logging.getLogger(__name__)

__all__ = ["Backend", "CircuitBreaker", "StereoRouter", "build_router"]

# cluster_breaker_state gauge encoding (docs/fault_tolerance.md).
_BREAKER_LEVEL = {"closed": 0, "open": 1, "half_open": 2}


class CircuitBreaker:
    """Per-backend circuit breaker — pure policy, injected clock, no I/O.

    ``closed`` -> ``open`` after ``fail_threshold`` consecutive
    failures; ``open`` -> ``half_open`` once ``reset_s`` has elapsed (a
    single trial is admitted — half-open exclusivity); ``half_open`` ->
    ``closed`` on success, back to ``open`` (fresh reset window) on
    failure.  Probe-driven recovery is deliberately two-step: the first
    healthy probe after the reset window moves ``open`` ->
    ``half_open`` and returns, the NEXT healthy verdict closes — one
    lucky probe mid-flap never slams the breaker shut.

    A request FAILURE is a transport failure (connect / response /
    timeout phase).  Any HTTP reply — including a 503 shed — proves the
    backend responsive and counts as success; load problems are the
    spill/backoff machinery's job, not the breaker's.

    ``listener(state)`` fires after each transition, outside the lock
    (wired to the ``cluster_breaker_*`` metric families).
    """

    def __init__(self, fail_threshold: int, reset_s: float,
                 clock=time.monotonic, listener=None):
        self.fail_threshold = max(1, int(fail_threshold))
        self.reset_s = reset_s
        self._clock = clock
        self._listener = listener
        self._lock = threading.Lock()
        self._state = "closed"  # guarded_by: _lock
        self._failures = 0  # guarded_by: _lock
        self._opened_at = 0.0  # guarded_by: _lock
        self._trial_inflight = False  # guarded_by: _lock

    def current(self) -> str:
        with self._lock:
            return self._state

    def _notify(self, fired: Optional[str]) -> None:
        # Listener dispatch stays OUTSIDE _lock: it touches metric
        # series locks and must never nest under breaker state.
        if fired is not None and self._listener is not None:
            self._listener(fired)

    def _open_locked(self) -> str:  # guarded_by: _lock
        self._opened_at = self._clock()
        self._failures = 0
        self._trial_inflight = False
        self._state = "open"
        return self._state

    def allow_request(self) -> bool:
        """Admission check at backend-pick time.  While ``half_open``
        at most one trial request is in flight until its verdict
        lands (``record_success`` / ``record_failure``)."""
        fired = None
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                if self._clock() - self._opened_at >= self.reset_s:
                    self._state = "half_open"
                    fired = self._state
                    self._trial_inflight = True
                    allowed = True
                else:
                    allowed = False
            else:  # half_open: single-trial exclusivity
                allowed = not self._trial_inflight
                if allowed:
                    self._trial_inflight = True
        self._notify(fired)
        return allowed

    def record_success(self) -> None:
        fired = None
        with self._lock:
            if self._state == "half_open":
                self._state = "closed"
                fired = self._state
            self._failures = 0
            self._trial_inflight = False
        self._notify(fired)

    def record_failure(self) -> None:
        fired = None
        with self._lock:
            if self._state == "half_open":
                fired = self._open_locked()
            elif self._state == "closed":
                self._failures += 1
                if self._failures >= self.fail_threshold:
                    fired = self._open_locked()
            else:
                # Already open: the reset window keeps aging — repeated
                # failures must not push recovery out forever.
                self._trial_inflight = False
        self._notify(fired)

    def on_probe(self, ok: bool) -> None:
        """Fold one health-probe verdict in (two-step recovery)."""
        if not ok:
            self.record_failure()
            return
        fired = None
        with self._lock:
            if self._state == "open":
                if self._clock() - self._opened_at >= self.reset_s:
                    self._state = "half_open"
                    fired = self._state
            elif self._state == "half_open":
                self._state = "closed"
                self._failures = 0
                self._trial_inflight = False
                fired = self._state
            else:
                self._failures = 0
        self._notify(fired)


class Backend:
    """One backend server plus the router's view of its health.

    The keyword arguments keep the bare ``Backend(bid, host, port)``
    construction (unit tests, tools) working: they get a default
    breaker that never reports transitions."""

    def __init__(self, bid: int, host: str, port: int,
                 fail_threshold: int = 2, breaker_reset_s: float = 5.0,
                 clock=time.monotonic, breaker_listener=None):
        self.bid = bid
        self.name = f"b{bid}"
        self.host = host
        self.port = port
        # breaker_listener receives (backend_name, new_state).
        self.breaker = CircuitBreaker(
            fail_threshold, breaker_reset_s, clock=clock,
            listener=(None if breaker_listener is None else
                      (lambda state: breaker_listener(self.name, state))))
        self._lock = threading.Lock()
        self.live = False  # guarded_by: _lock
        self.ready = False  # guarded_by: _lock
        self.draining = False  # guarded_by: _lock
        self.drained = False  # guarded_by: _lock
        self._queue_depth = 0  # guarded_by: _lock
        self._probe_failures = 0  # guarded_by: _lock
        self.inflight = 0  # guarded_by: _lock
        self._session_bytes = 0  # guarded_by: _lock
        self._session_budget_mb = 0.0  # guarded_by: _lock

    def routable(self) -> bool:
        with self._lock:
            return self.live and self.ready and not self.draining

    def outstanding(self) -> int:
        with self._lock:
            return self.inflight + self._queue_depth

    def session_memory(self) -> Tuple[int, float]:
        """(accounted session bytes, configured budget MiB) from the
        last successful probe — (0, 0.0) for a backend without
        streaming or a byte budget."""
        with self._lock:
            return self._session_bytes, self._session_budget_mb

    def begin(self) -> None:
        with self._lock:
            self.inflight += 1

    def end(self) -> None:
        with self._lock:
            self.inflight -= 1

    def mark_unreachable(self) -> None:
        """Called on an in-flight connection failure: stop routing here
        immediately instead of waiting out the probe interval."""
        with self._lock:
            self.live = False
            self.ready = False

    def mark_draining(self) -> None:
        with self._lock:
            self.draining = True

    def on_probe(self, health: Optional[Dict], fail_after: int) -> None:
        """Fold one probe result (None = probe failed) into the state."""
        # Feed the breaker first, outside _lock (its own lock + the
        # transition listener must never nest under backend state).
        self.breaker.on_probe(health is not None)
        with self._lock:
            if health is None:
                self._probe_failures += 1
                if self._probe_failures >= fail_after:
                    self.live = False
                    self.ready = False
                return
            self._probe_failures = 0
            self.live = bool(health.get("live", True))
            self.ready = bool(health.get("ready", True))
            # Trust the backend's own draining report when it makes one:
            # a drained backend RESTARTED at the same address reports
            # draining=false and must rejoin rotation (scale-in undo).
            # Only a backend that predates the flag keeps the router's
            # local mark_draining decision sticky.
            if "draining" in health:
                self.draining = bool(health["draining"])
            self.drained = bool(health.get("drained", False))
            self._queue_depth = int(health.get("queue_depth", 0) or 0)
            # Session-memory signals from the backend's stream block
            # (stream/session.py byte accounting) — the router's
            # memory-pressure autoscale input.
            stream = health.get("stream") or {}
            self._session_bytes = int(stream.get("session_bytes", 0) or 0)
            self._session_budget_mb = float(
                stream.get("session_budget_mb", 0.0) or 0.0)

    def state(self) -> str:
        with self._lock:
            if not self.live:
                return "unreachable"
            if self.draining:
                return "drained" if self.drained else "draining"
            return "ready" if self.ready else "starting"

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "host": self.host, "port": self.port,
                "live": self.live, "ready": self.ready,
                "draining": self.draining, "drained": self.drained,
                "queue_depth": self._queue_depth,
                "inflight": self.inflight,
                "probe_failures": self._probe_failures,
                "breaker": self.breaker.current(),
            }


def _http_json(host: str, port: int, method: str, path: str,
               timeout: float, body: Optional[bytes] = None,
               headers: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict]:
    """One short JSON request to a backend (probes, drain forwarding)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, (json.loads(raw) if raw else {})
    finally:
        conn.close()


class _ProbeSchedule:
    """Deterministic per-backend probe cadence with thundering-herd
    jitter — pure policy, clock injected through explicit ``now``
    arguments (unit-testable without sockets or sleeps).

    With N backends on one synchronized period every probe round lands
    N near-simultaneous /healthz hits on the fleet (and on any shared
    health path behind it).  Instead each backend gets a deterministic
    fraction ``frac = (crc32(name) % 997) / 997`` spreading both the
    PHASE (first probe at ``frac * interval``) and the PERIOD
    (``interval * (1 + frac/2)``) — distinct backends decorrelate and
    STAY decorrelated instead of re-synchronizing every lcm, and the
    schedule is identical across router restarts (no RNG)."""

    def __init__(self, names, interval_s: float, now: float = 0.0):
        self.interval_s = interval_s
        self._period: Dict[str, float] = {}
        self._next: Dict[str, float] = {}
        for name in names:
            frac = (zlib.crc32(name.encode()) % 997) / 997.0
            self._period[name] = interval_s * (1.0 + 0.5 * frac)
            self._next[name] = now + frac * interval_s

    def period_s(self, name: str) -> float:
        return self._period[name]

    def due(self, now: float) -> List[str]:
        """Backends due at ``now``, each advanced PAST ``now`` — a late
        round never bursts catch-up probes."""
        out = []
        for name in sorted(self._next, key=self._next.get):
            t = self._next[name]
            if t <= now:
                out.append(name)
                period = self._period[name]
                missed = int((now - t) // period) + 1
                self._next[name] = t + missed * period
        return out

    def next_wake(self, now: float) -> float:
        """Seconds until the earliest pending probe (>= 0)."""
        return max(min(self._next.values()) - now, 0.0)


class _Prober(threading.Thread):
    """Polls each backend's /healthz on its own jittered cadence
    (``_ProbeSchedule``) and refreshes the cluster gauges — the
    router's only source of backend readiness besides in-flight
    connection failures."""

    def __init__(self, router: "StereoRouter"):
        super().__init__(name="router-prober", daemon=True)
        self.router = router
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def _probe_backend(self, b: Backend) -> None:
        cfg = self.router.config
        try:
            status, health = _http_json(
                b.host, b.port, "GET", "/healthz",
                timeout=cfg.probe_timeout_s)
            b.on_probe(health if status == 200 else None,
                       cfg.fail_after)
            if status != 200:
                self.router.cluster_metrics.probe_failures.labels(
                    replica=b.name).inc()
        except (OSError, ValueError):
            # ValueError covers JSONDecodeError: a backend answering
            # non-JSON on /healthz (wrong port, an intermediary's
            # HTML error page) is a FAILED probe for that backend —
            # never an exception that aborts the round (or, at
            # startup, the router) and leaves the other backends'
            # health stale.
            b.on_probe(None, cfg.fail_after)
            self.router.cluster_metrics.probe_failures.labels(
                replica=b.name).inc()

    def probe_once(self) -> None:
        """Probe ALL backends synchronously (router start: the first
        routing decision needs every backend's health, jitter or not)."""
        for b in self.router.backends:
            self._probe_backend(b)
        self.router.refresh_gauges()

    def run(self) -> None:
        sched = _ProbeSchedule(
            [b.name for b in self.router.backends],
            self.router.config.probe_interval_s,
            now=time.monotonic())
        by_name = {b.name: b for b in self.router.backends}
        while not self._stop.is_set():
            due = sched.due(time.monotonic())
            if due:
                try:
                    for name in due:
                        self._probe_backend(by_name[name])
                    self.router.refresh_gauges()
                except Exception:  # pragma: no cover - defensive
                    logger.exception("health probe round failed")
            # 5 ms floor so a due-now edge never busy-spins.
            self._stop.wait(max(sched.next_wake(time.monotonic()),
                                0.005))


class _RouterHandler(JsonRequestHandler):
    server_version = "raftstereo-router/1.0"
    _log = logger
    # _send/_json/_read_body come from JsonRequestHandler — shared with
    # the backend server's handler so the two dialects cannot drift.

    # ------------------------------------------------------------- GET side

    def do_GET(self):
        rt: "StereoRouter" = self.server
        url = urlparse(self.path)
        if url.path == "/healthz":
            self._json(200, rt.health())
        elif url.path == "/metrics":
            rt.refresh_gauges()
            self._send(200, rt.cluster_metrics.render().encode(),
                       "text/plain; version=0.0.4")
        elif url.path == "/metrics/fleet":
            # Federated fleet exposition (obs/fleet.py): the router's
            # own families plus every backend's and the session tier's,
            # re-labeled with backend= — one scrape for the cluster.
            fs = rt.federate()
            self._send(200, fs.text.encode(),
                       "text/plain; version=0.0.4")
        elif url.path == "/debug/trace":
            qs = parse_qs(url.query)
            tid = (qs.get("trace_id") or [None])[0]
            if tid:
                # Cross-hop stitching (obs/stitch.py): fan out to every
                # fleet member's /debug/trace and return ONE span tree —
                # the router hop span parenting each backend's
                # admission -> queue_wait -> dispatch -> host_fetch.
                self._json(200, rt.stitched_trace(tid))
                return
            try:
                body, extra = trace_response(rt.tracer, url.query)
            except ValueError as e:
                self._json(400, {"error": f"bad query: {e}"})
                return
            self._send(200, body, "application/json", extra)
        elif url.path == "/debug/alerts":
            # One live burn-rate evaluation over a fresh federated
            # scrape (obs/alerts.py) — also refreshes the
            # fleet_alert_state{class=} gauges.
            self._json(200, rt.evaluate_alerts())
        elif url.path == "/debug/threads":
            self._send(200, dump_threads().encode(), "text/plain")
        elif url.path == "/debug/vars":
            hop = rt.cluster_metrics.router_latency
            self._json(200, {
                "backends": {b.name: b.snapshot() for b in rt.backends},
                "session_pins": rt.pin_count(),
                "autoscale": rt.autoscale_advice,
                # Live hop-latency percentiles (utils/profiling
                # quantile) — operators see p50/p99 without a
                # Prometheus stack.  null until the first forward.
                "latency": ({
                    "count": hop.count,
                    "hop_p50_ms": round(hop.quantile(0.5) * 1e3, 3),
                    "hop_p99_ms": round(hop.quantile(0.99) * 1e3, 3),
                } if hop.count else None),
                "tail": rt.tail.stats(),
                "alerts": rt.alert_summary(),
                "build": build_info(),
            })
        else:
            self._json(404, {"error": f"no such path {self.path!r}"})

    # ------------------------------------------------------------ POST side

    def _named_backend(self, rt: "StereoRouter",
                       raw: bytes) -> Optional["Backend"]:
        """Resolve the ``?backend=`` / ``{"backend": ...}`` target of an
        ops verb; replies 400 (and returns None) on an unknown name."""
        qs = parse_qs(urlparse(self.path).query)
        name = (qs.get("backend", [None])[0])
        if name is None and raw:
            try:
                name = json.loads(raw).get("backend")
            except Exception:
                name = None
        backend = next((b for b in rt.backends if b.name == name), None)
        if backend is None:
            self._json(400, {"error": f"unknown backend {name!r}; choose "
                                      f"from "
                                      f"{[b.name for b in rt.backends]}"})
        return backend

    def _drain(self, rt: "StereoRouter", raw: bytes) -> None:
        """POST /debug/drain: take one backend out of rotation and
        forward the drain; the backend finishes running batches and its
        /healthz flips to drained (poll it through GET /healthz here)."""
        backend = self._named_backend(rt, raw)
        if backend is None:
            return
        backend.mark_draining()
        rt.refresh_gauges()
        try:
            status, reply = _http_json(
                backend.host, backend.port, "POST", "/debug/drain",
                timeout=rt.config.probe_timeout_s)
        except (OSError, ValueError) as e:  # incl. non-JSON reply
            self._json(502, {"error": f"drain forward failed: {e}",
                             "backend": backend.name})
            return
        self._json(status, {"backend": backend.name, "drain": reply})

    def _restart(self, rt: "StereoRouter", raw: bytes) -> None:
        """POST /debug/restart: the zero-downtime rolling-restart verb —
        drain the backend, wait (bounded) for its in-flight work to
        finish, migrate every pinned session warm to the remaining
        backends, then hand back to the operator.  The operator restarts
        or upgrades the process (``warmup_async``) and the readiness
        probe gates its rejoin; migrated sessions never see a cold
        frame."""
        backend = self._named_backend(rt, raw)
        if backend is None:
            return
        backend.mark_draining()
        rt.refresh_gauges()
        try:
            _, drain_reply = _http_json(
                backend.host, backend.port, "POST", "/debug/drain",
                timeout=rt.config.probe_timeout_s)
        except (OSError, ValueError) as e:
            self._json(502, {"error": f"drain forward failed: {e}",
                             "backend": backend.name})
            return
        drained = rt.wait_drained(backend)
        migrated = rt.migrate_all_from(backend)
        rt.refresh_gauges()
        self._json(200, {
            "backend": backend.name,
            "drain": drain_reply,
            "drained": drained,
            "migrated": migrated,
            "next": "restart the backend process (warmup_async "
                    "recommended); the readiness probe gates its rejoin",
        })

    def _arm_faults(self, rt: "StereoRouter", raw: bytes) -> None:
        """POST /debug/faults ``{"faults": SPEC}``: arm serving-plane
        fault entries at runtime — the seam the loadgen chaos
        controller drives plan entries through against trace time
        (loadgen/chaos.py, docs/fault_tolerance.md)."""
        try:
            spec = json.loads(raw or b"{}").get("faults", "")
            armed = rt.fault_plan.extend(str(spec or ""))
        except ValueError as e:
            self._json(400, {"error": f"bad fault spec: {e}"})
            return
        self._json(200, {"armed": [f.spec() for f in armed]})

    def _header_deadline(self) -> Optional[float]:
        """Client deadline budget from ``X-Deadline-Ms`` (None when
        absent or unparseable — a garbled optional header must not
        fail a request that never asked for a deadline contract)."""
        raw = self.headers.get("X-Deadline-Ms")
        if raw is None:
            return None
        try:
            return max(float(raw), 0.0)
        except ValueError:
            return None

    def do_POST(self):
        rt: "StereoRouter" = self.server
        path = urlparse(self.path).path
        if path == "/predict" and wire.is_wire_content_type(
                self.headers.get("Content-Type")):
            # Binary frames stream through without full-body buffering —
            # the whole point of the wire format at router scale
            # (docs/wire_format.md "Router forwarding").
            self._predict_stream(rt)
            return
        raw = self._read_body(rt.config.max_body_mb)
        if raw is None:
            return
        if path == "/debug/drain":
            self._drain(rt, raw)
            return
        if path == "/debug/restart":
            self._restart(rt, raw)
            return
        if path == "/debug/faults":
            self._arm_faults(rt, raw)
            return
        if path != "/predict":
            self._json(404, {"error": f"no such path {self.path!r}"})
            return
        # Same 64-char cap the backend applies (server.py): a longer
        # client-chosen id would be truncated there and split the trace
        # between router and backend spans (it is also client-controlled
        # data stored in the span ring — bound it).
        rid = (self.headers.get("X-Request-Id") or "")[:64] \
            or rt.tracer.new_trace_id()
        try:
            payload = json.loads(raw)
            session_id = payload.get("session_id")
        except Exception as e:
            self._json(400, {"error": f"bad request: {e}"},
                       {"X-Request-Id": rid})
            return
        status, body, ctype, headers = rt.route_predict(
            raw, session_id, rid, accept=self.headers.get("Accept"),
            deadline_ms=self._header_deadline(),
            trace=self.trace_of(rid))
        self._send(status, body, ctype, headers)

    def _predict_stream(self, rt: "StereoRouter") -> None:
        """Binary /predict: peek the fixed header + JSON meta (bounded,
        small — the session pin needs ``session_id``), then hand the
        connection to ``route_predict_stream`` which pumps the remaining
        planes rfile -> backend socket in WIRE_CHUNK slices.  The full
        body never exists in router memory."""
        rid = (self.headers.get("X-Request-Id") or "")[:64] \
            or rt.tracer.new_trace_id()
        reject = self._reject_body(rt.config.max_body_mb)
        if reject is not None:
            code, payload = reject
            self._json(code, payload, {"X-Request-Id": rid})
            return
        length = self._body_length

        def bad(msg: str) -> None:
            # The body is partially read: nothing further on this
            # connection can be framed.
            self.close_connection = True
            self._json(400, {"error": msg}, {"X-Request-Id": rid})

        if length < wire.HEADER_SIZE:
            bad(f"body too short for a wire frame ({length} bytes)")
            return
        parts: List[bytes] = []
        if not self._read_body_stream(wire.HEADER_SIZE, parts.append):
            return  # short read: connection already marked close
        head = b"".join(parts)
        try:
            hdr = wire.parse_header(
                head, expect=wire.FRAME_REQUEST,
                max_payload_bytes=int(rt.config.max_body_mb * 2 ** 20) * 8)
        except wire.WireError as e:
            # WireVersionError rides through str(e) naming the
            # supported range — same 400 contract as the backend.
            bad(str(e))
            return
        meta_len = hdr["meta_len"]
        if wire.HEADER_SIZE + meta_len > length:
            bad("meta_len overruns Content-Length")
            return
        meta_parts: List[bytes] = []
        if meta_len and not self._read_body_stream(meta_len,
                                                   meta_parts.append):
            return
        meta_raw = b"".join(meta_parts)
        session_id = None
        if meta_raw:
            try:
                meta = json.loads(meta_raw)
                session_id = (meta.get("fields") or {}).get("session_id")
            except Exception as e:
                bad(f"bad frame meta: {e}")
                return
        rt.route_predict_stream(self, head + meta_raw,
                                length - wire.HEADER_SIZE - meta_len,
                                session_id, rid,
                                accept=self.headers.get("Accept"),
                                deadline_ms=self._header_deadline(),
                                trace=self.trace_of(rid))


class StereoRouter(ThreadingHTTPServer):
    """HTTP front-end owning the backend table, prober, pins, metrics.

    ``config.port == 0`` binds an ephemeral port (read it from
    ``router.port``).  The router exports ONLY the ``cluster_*``
    families — per-request serving metrics live on the backends.
    """

    daemon_threads = True

    def __init__(self, config: RouterConfig,
                 tracer: Optional[Tracer] = None,
                 fault_plan: Optional[FaultPlan] = None):
        assert config.backends, "a router needs at least one backend"
        self.config = config
        # Metrics before backends: the breaker transition listener
        # writes cluster_breaker_* the moment any breaker moves.
        self.registry = MetricsRegistry()
        self.cluster_metrics = ClusterMetrics(self.registry)
        self.backends: List[Backend] = [
            Backend(i, host, port,
                    fail_threshold=config.fail_after,
                    breaker_reset_s=config.breaker_reset_s,
                    breaker_listener=self._on_breaker)
            for i, (host, port) in enumerate(config.backends)]
        self.tracer = tracer or Tracer(capacity=config.trace_buffer)
        # FULL forward latency (connect -> last response byte) feeding
        # the hedge delay.  Intentionally NOT a registered family:
        # cluster_router_hop_latency_seconds excludes backend compute
        # by design, and the hedge policy needs the end-to-end p99.
        self._fwd_latency = LatencyHistogram()
        # Serving-plane fault plan (utils/faults.py): armed from
        # RAFTSTEREO_FAULTS at construction, extended at runtime over
        # POST /debug/faults by the chaos controller.
        self.fault_plan = (fault_plan if fault_plan is not None
                           else FaultPlan.from_env()).arm()
        # session_id -> backend bid (same LRU pin policy — and the same
        # PinTable implementation — as the in-process dispatcher: an
        # evicted pin behaves exactly like a lost session, the next
        # frame re-pins and runs cold).
        self._pins = PinTable(config.session_pin_limit)
        # Export-in-flight markers: at most one migration per session at
        # a time (a per-frame re-pin handoff racing the restart sweep
        # would pull the same state twice; the backend store's monotonic
        # import guard makes the race safe, the marker makes it cheap).
        self._migrate_lock = threading.Lock()
        self._migrating = set()  # guarded_by: _migrate_lock
        # Streaming-forward instrumentation (stream_stats / the
        # no-full-buffering assertion in tests): peak is the largest
        # single chunk the binary path ever staged, NOT a body size.
        self._stream_lock = threading.Lock()
        self._stream_requests = 0  # guarded_by: _stream_lock
        self._stream_peak_chunk = 0  # guarded_by: _stream_lock
        capacity = (load_capacity_model(config.capacity_model)
                    if config.capacity_model else None)
        self._autoscaler = Autoscaler(capacity=capacity,
                                      target_rps=config.target_rps)
        self._advice: Dict[str, object] = {}
        # Fleet observability plane (docs/observability.md): tail-based
        # trace retention, the /metrics/fleet federator, and the live
        # burn-rate alerts whose page-qualified burn feeds the
        # autoscaler (refresh_gauges).
        self.tail = TailSampler(capacity=config.tail_ring)
        self._federator = FleetFederator(
            self.registry, targets_fn=self._fleet_targets,
            timeout_s=config.fleet_timeout_s)
        self.alerts = BurnRateAlerts(
            self.registry,
            classes=(AlertClass(
                max_error_rate=config.alert_error_budget,
                max_shed_rate=config.alert_shed_budget),),
            fast_window_s=config.alert_window_s,
            page_burn=config.alert_page_burn)
        self._prober = _Prober(self)
        super().__init__((config.host, config.port), _RouterHandler)

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> "StereoRouter":
        """Probe once synchronously (so a freshly built router already
        knows which backends are ready), then start the prober."""
        self._prober.probe_once()
        self._prober.start()
        return self

    def close(self) -> None:
        self._prober.stop()
        self.shutdown()
        self.server_close()

    # ------------------------------------------------------------- routing

    def pin_count(self) -> int:
        return len(self._pins)

    def health(self) -> Dict[str, object]:
        """Router /healthz: the router is live by construction; ready
        means at least one backend is routable."""
        return {
            "status": "ok",
            "live": True,
            "ready": any(b.routable() for b in self.backends),
            "backends": {b.name: dict(b.snapshot(), state=b.state())
                         for b in self.backends},
            "session_pins": self.pin_count(),
        }

    def _ready_backends(self, exclude=()) -> List[Backend]:
        ready = [b for b in self.backends
                 if b.routable() and b.bid not in exclude]
        return sorted(ready, key=lambda b: (b.outstanding(), b.bid))

    def _pin_backend(self, session_id: str,
                     exclude=()) -> Optional[Backend]:
        """Sticky backend for a session, re-pinning when its backend is
        gone or draining — with a warm handoff attempt from the old
        backend first (a frame arriving inside the drain window takes
        this path and still gets its state; a killed backend's handoff
        fails over to the documented cold_lost fallback)."""
        bid, repinned, old = self._pins.pin(
            session_id,
            still_ok=lambda b: self.backends[b].routable()
            and b not in exclude,
            choose=lambda: (lambda c: c[0].bid if c else None)(
                self._ready_backends(exclude)))
        if bid is None:
            return None
        backend = self.backends[bid]
        if repinned:
            self.cluster_metrics.session_repins.labels(
                reason=self._repin_reason(old)).inc()
            self._handoff(session_id,
                          self.backends[old] if old is not None else None,
                          backend)
        return backend

    def _repin_reason(self, old_bid: Optional[int]) -> str:
        """Why the old pin was unusable (the repins metric label)."""
        if old_bid is None:
            return "evicted"
        state = self.backends[old_bid].state()
        if state == "unreachable":
            return "failed"
        if state in ("draining", "drained"):
            return "draining"
        return "evicted"

    # ----------------------------------------------------------- migration

    def _handoff(self, session_id: str, src: Optional[Backend],
                 dst: Backend) -> Optional[str]:
        """Move one session's state ``src -> dst`` over the wire; returns
        the counted outcome, or None when another thread is already
        migrating this session (its outcome is counted there)."""
        with self._migrate_lock:
            if session_id in self._migrating:
                return None
            self._migrating.add(session_id)
        try:
            return self._migrate_session(session_id, src, dst)
        finally:
            with self._migrate_lock:
                self._migrating.discard(session_id)

    def _migrate_session(self, session_id: str, src: Optional[Backend],
                         dst: Backend) -> str:
        """GET the snapshot off ``src``, POST it into ``dst`` (bodies
        relayed verbatim — the router never decodes the disparity, so
        the move stays bitwise).  When the direct move fails AND a
        durable session tier is configured, resume from the tier's
        latest write-behind snapshot instead — a SIGKILLed home backend
        no longer costs the session its warmth.  Only with no tier (or
        the tier also missing/unreachable) does the failure remain the
        documented cold_lost fallback: the next frame simply runs
        cold."""
        outcome = "cold_lost"
        if src is not None and src.bid != dst.bid:
            try:
                status, snapshot = _http_json(
                    src.host, src.port, "GET",
                    "/debug/sessions/" + quote(session_id, safe=""),
                    timeout=self.config.probe_timeout_s)
                if status == 200 and snapshot:
                    status2, reply = _http_json(
                        dst.host, dst.port, "POST", "/debug/sessions",
                        timeout=self.config.probe_timeout_s,
                        body=json.dumps(snapshot).encode(),
                        headers={"Content-Type": "application/json"})
                    if status2 == 200:
                        outcome = str(reply.get("outcome", "cold_lost"))
            except (OSError, ValueError):
                outcome = "cold_lost"
        if outcome == "cold_lost" and self.config.session_tier is not None:
            outcome = self._resume_from_tier(session_id, dst)
        self.cluster_metrics.session_handoffs.labels(
            outcome=outcome).inc()
        return outcome

    def _resume_from_tier(self, session_id: str, dst: Backend) -> str:
        """Pull the tier's latest snapshot for ``session_id`` into
        ``dst`` (same verbatim relay as the direct path — the tier
        stores exactly the wire body the backends exchange).  A miss or
        an unreachable tier is the cold_lost fallback, never an error;
        a ``cold_schema`` reply from ``dst`` passes through (mixed
        fleets refuse a foreign codec cleanly, docs/streaming.md)."""
        host, port = self.config.session_tier
        try:
            status, snapshot = _http_json(
                host, port, "GET",
                "/debug/sessions/" + quote(session_id, safe=""),
                timeout=self.config.probe_timeout_s)
            if status != 200 or not snapshot:
                return "cold_lost"
            status2, reply = _http_json(
                dst.host, dst.port, "POST", "/debug/sessions",
                timeout=self.config.probe_timeout_s,
                body=json.dumps(snapshot).encode(),
                headers={"Content-Type": "application/json"})
            if status2 == 200:
                return str(reply.get("outcome", "cold_lost"))
        except (OSError, ValueError):
            pass
        return "cold_lost"

    def migrate_all_from(self, backend: Backend) -> Dict[str, str]:
        """Move every session pinned to ``backend`` to the next ready
        backend (the drain/restart sweep): state first, then the pin —
        a CAS, so a concurrent ``pin()`` decision wins over the sweep."""
        outcomes: Dict[str, str] = {}
        for sid in self._pins.pinned_to(backend.bid):
            cands = self._ready_backends(exclude=(backend.bid,))
            if not cands:
                break
            dst = cands[0]
            outcome = self._handoff(sid, backend, dst)
            if outcome is None:
                continue  # raced a per-frame handoff; counted there
            outcomes[sid] = outcome
            cur = self._pins.peek(sid)
            if cur in (backend.bid, None):
                self._pins.reassign(sid, cur, dst.bid)
        return outcomes

    def wait_drained(self, backend: Backend,
                     timeout_s: float = 10.0) -> bool:
        """Poll the backend's /healthz until it reports drained
        (bounded).  The session-lock serialization already makes exports
        consistent; waiting for the drain keeps the restart sweep
        deterministic — every last frame's state is in the store before
        the sweep reads it."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                status, health = _http_json(
                    backend.host, backend.port, "GET", "/healthz",
                    timeout=self.config.probe_timeout_s)
                if status == 200:
                    backend.on_probe(health, self.config.fail_after)
                    if health.get("drained"):
                        return True
            except (OSError, ValueError):
                pass
            time.sleep(0.05)
        return False

    def _record(self, backend: Backend, outcome: str) -> None:
        self.cluster_metrics.dispatch.labels(
            replica=backend.name, outcome=outcome).inc()

    def _on_breaker(self, name: str, state: str) -> None:
        """CircuitBreaker transition listener (fires outside the
        breaker lock): export the move and the new level."""
        cm = self.cluster_metrics
        cm.breaker_transitions.labels(backend=name, to=state).inc()
        cm.breaker_state.labels(backend=name).set(_BREAKER_LEVEL[state])

    def refresh_gauges(self) -> None:
        cm = self.cluster_metrics
        states: Dict[str, int] = {}
        for b in self.backends:
            states[b.state()] = states.get(b.state(), 0) + 1
            cm.queue_depth.labels(replica=b.name).set(b.outstanding())
            cm.breaker_state.labels(backend=b.name).set(
                _BREAKER_LEVEL[b.breaker.current()])
        cm.set_states(states)
        ready = [b for b in self.backends if b.routable()]
        # Utilization proxy without knowing backend batch capacity: the
        # fraction of ready backends with work outstanding.
        cm.utilization.set(
            round(sum(1 for b in ready if b.outstanding() > 0)
                  / len(ready), 4) if ready else 0.0)
        # Feed the recommendation loop (ops/autoscale.py): advice lands
        # in /debug/vars and the cluster_autoscale_recommendation gauge.
        shed = sum(child.value for labels, child in cm.dispatch.series()
                   if labels[1] == "shed")
        # Session-memory pressure aggregated from the backends' probe
        # reports (stream.session_bytes / session_budget_mb on
        # /healthz): fleet bytes over fleet budget, among backends that
        # configured a budget.  0.0 when none did.
        mem = [b.session_memory() for b in ready]
        budget = sum(m[1] for m in mem) * 2 ** 20
        memory_pressure = (round(sum(m[0] for m in mem
                                     if m[1] > 0) / budget, 4)
                           if budget > 0 else 0.0)
        advice = self._autoscaler.observe(
            ready=len(ready), utilization=cm.utilization.value,
            shed_total=shed, memory_pressure=memory_pressure,
            alert_burn=self.alerts.max_burn())
        cm.autoscale_recommendation.set(advice["delta"])
        cap = advice.get("capacity")
        # 0.0 without a model (same convention as the dispatcher).
        cm.capacity_headroom.set(cap["headroom"] if cap else 0.0)
        self._advice = advice

    @property
    def autoscale_advice(self) -> Dict[str, object]:
        return self._advice

    # ------------------------------------------------ fleet observability

    def _fleet_targets(self) -> List[Tuple[str, str, int]]:
        """Live (label, host, port) scrape/stitch targets: every
        registered backend plus the session tier when configured.
        Called per federation so drain/rejoin is always reflected."""
        targets = [(b.name, b.host, b.port) for b in self.backends]
        if self.config.session_tier is not None:
            host, port = self.config.session_tier
            targets.append(("session_tier", host, port))
        return targets

    def federate(self):
        """One federated /metrics/fleet render.  The local text is
        produced AFTER the foreign scrapes (obs/fleet.py federate doc)
        and with gauges freshly refreshed, so the render carries both
        its own scrape-failure increments and live advice."""
        def local_text() -> str:
            self.refresh_gauges()
            return self.registry.render()
        return self._federator.federate(local_text)

    def evaluate_alerts(self) -> Dict:
        """Fresh federated scrape -> one burn-rate evaluation.  The
        p99 fed to the latency bound is the FULL forward p99 (connect
        -> last byte) — what a client of this router experiences."""
        fs = self.federate()
        p99 = (self._fwd_latency.quantile(0.99)
               if self._fwd_latency.count else None)
        return self.alerts.observe(fs, p99_s=p99)

    def alert_summary(self) -> Optional[Dict]:
        """Compact /debug/vars view of the last alert evaluation
        (None until GET /debug/alerts has evaluated once)."""
        last = self.alerts.last()
        if last is None:
            return None
        return {"classes": [{"class": c["class"],
                             "state": c["state_name"],
                             "burn": c["burn"]}
                            for c in last["classes"]],
                "page_burn": last["page_burn"]}

    def stitched_trace(self, trace_id: str) -> Dict:
        """Cross-hop stitch (obs/stitch.py): the router's own spans
        plus every fleet member's /debug/trace export for this trace,
        merged into one span tree.  An unreachable member becomes a
        ``gaps`` entry — the tree is partial, never a 500."""
        sources: List[Tuple[str, Optional[Dict]]] = [
            ("router", self.tracer.to_chrome(trace_id=trace_id))]
        for label, host, port in self._fleet_targets():
            try:
                status, doc = _http_json(
                    host, port, "GET",
                    "/debug/trace?trace_id=" + quote(trace_id, safe=""),
                    timeout=self.config.fleet_timeout_s)
                sources.append((label, doc if status == 200 else None))
            except (OSError, ValueError):
                sources.append((label, None))
        return stitch_sources(trace_id, sources)

    def _tail_offer(self, trace_id: Optional[str], t0: float,
                    status: int) -> None:
        """Feed the tail sampler one finished route: the slow threshold
        is the live full-forward p99 once enough samples exist (early
        traffic has no meaningful tail to compare against)."""
        thr = (self._fwd_latency.quantile(0.99)
               if self._fwd_latency.count >= 20 else None)
        self.tail.offer(trace_id, time.perf_counter() - t0, status,
                        threshold_s=thr)

    def _forward(self, backend: Backend, raw: bytes, rid: str,
                 accept: Optional[str] = None,
                 deadline_left_ms: Optional[float] = None,
                 trace_header: Optional[str] = None
                 ) -> Tuple[str, int, bytes, str, Dict[str, str]]:
        """One proxy attempt.  Returns (phase, status, body, ctype,
        headers): phase ``"ok"`` carries a backend reply; ``"connect"``
        failed before the request reached the backend (always safe to
        retry); ``"response"`` failed after (only idempotent work may
        retry); ``"timeout"`` means the backend may still be computing.
        The client's ``Accept`` forwards verbatim so the BACKEND decides
        the response dialect — the router relays bytes, it never
        negotiates.  ``trace_header`` is the pre-formatted
        ``X-Trace-Context`` value continuing this hop's trace (the
        parent is the hop span whose id was minted before the forward);
        None keeps the wire header-compatible with pre-PR 20 callers."""
        conn = http.client.HTTPConnection(
            backend.host, backend.port,
            timeout=self.config.request_timeout_s)
        headers_out = {"Content-Type": "application/json",
                       "X-Request-Id": rid}
        if trace_header:
            headers_out[TRACE_HEADER] = trace_header
        if accept:
            headers_out["Accept"] = accept
        if deadline_left_ms is not None:
            # Deadline propagation: the budget the BACKEND sees already
            # has this hop's queueing/backoff elapsed subtracted — it
            # never computes an answer the client has abandoned.
            headers_out["X-Deadline-Ms"] = (
                f"{max(deadline_left_ms, 1.0):.0f}")
        try:
            try:
                conn.request("POST", "/predict", body=raw,
                             headers=headers_out)
            except OSError:
                backend.mark_unreachable()
                return "connect", 0, b"", "application/json", {}
            try:
                resp = conn.getresponse()
                body = resp.read()
            except socket.timeout:
                return "timeout", 0, b"", "application/json", {}
            except (http.client.HTTPException, OSError):
                backend.mark_unreachable()
                return "response", 0, b"", "application/json", {}
            headers = {"X-Request-Id": resp.headers.get("X-Request-Id",
                                                        rid),
                       "X-Backend": backend.name}
            ctype = resp.headers.get("Content-Type", "application/json")
            return "ok", resp.status, body, ctype, headers
        finally:
            conn.close()

    def _forward_timed(self, backend: Backend, raw: bytes, rid: str,
                       accept: Optional[str] = None,
                       deadline_left_ms: Optional[float] = None,
                       trace_header: Optional[str] = None
                       ) -> Tuple[str, int, bytes, str, Dict[str, str]]:
        """``_forward`` plus the bookkeeping every attempt owes:
        inflight begin/end, the breaker verdict (any HTTP reply =
        responsive = success), and the full-forward latency sample the
        hedge delay derives its p99 from."""
        backend.begin()
        t = time.perf_counter()
        try:
            result = self._forward(backend, raw, rid, accept,
                                   deadline_left_ms, trace_header)
        finally:
            backend.end()
        if result[0] == "ok":
            backend.breaker.record_success()
            self._fwd_latency.observe(time.perf_counter() - t)
        else:
            backend.breaker.record_failure()
        return result

    def _pick_cold(self, tried: List[int]) -> Optional[Backend]:
        """Least-outstanding ready backend whose breaker admits the
        request.  A breaker-open backend is skipped (recorded as
        ``breaker_open``) and the request SPILLS to the next ready
        backend.  Session pins bypass this path on purpose: stickiness
        beats breaker pessimism — a pinned backend that is truly down
        fails its forward, which re-feeds the breaker anyway."""
        for b in self._ready_backends(exclude=tuple(tried)):
            if b.breaker.allow_request():
                return b
            self._record(b, "breaker_open")
        return None

    def _pick_hedge(self, tried: List[int]) -> Optional[Backend]:
        """Hedge target: next admitting ready backend not yet tried
        (no metric on a skip — a hedge that finds no spare backend
        simply does not fire)."""
        for b in self._ready_backends(exclude=tuple(tried)):
            if b.breaker.allow_request():
                return b
        return None

    def _hedge_delay_s(self) -> Optional[float]:
        """Seconds to wait before hedging a cold JSON request, or None
        when hedging is disabled (``hedge_floor_ms == 0``, the
        default).  Tracks the live full-forward p99 once enough
        samples exist so the hedge only fires on genuinely tail-slow
        forwards; the floor guards the cold-start phase where p99 is
        noise."""
        cfg = self.config
        if cfg.hedge_floor_ms <= 0:
            return None
        floor = cfg.hedge_floor_ms / 1e3
        if self._fwd_latency.count >= cfg.hedge_min_samples:
            return max(floor, self._fwd_latency.quantile(0.99))
        return floor

    def _forward_hedged(self, primary: Backend, raw: bytes, rid: str,
                        accept: Optional[str], tried: List[int],
                        is_session: bool,
                        deadline_left_ms: Optional[float] = None,
                        trace_header: Optional[str] = None
                        ) -> Tuple[Backend, str, int, bytes, str,
                                   Dict[str, str]]:
        """Forward with an optional hedged second request (cold JSON
        only — idempotent per the PR 8 ``_RetrySafe`` analysis; never
        sessions, and the binary stream path cannot replay its body).
        The primary runs in a worker thread; if no reply lands within
        the hedge delay a second request fires at the next admitting
        backend and the first OK reply wins.  The loser's socket is
        abandoned — its thread ends when its own timeout fires, and
        its breaker/latency bookkeeping still lands via
        ``_forward_timed``.  Returns (backend_used, phase, status,
        body, ctype, headers)."""
        delay = None if is_session else self._hedge_delay_s()
        if delay is None:
            return (primary,) + self._forward_timed(
                primary, raw, rid, accept, deadline_left_ms,
                trace_header)
        results: "queue.Queue" = queue.Queue()

        def attempt(b: Backend) -> None:
            # Both contenders carry the SAME trace header: each backend
            # request span parents under the one hop span that covers
            # this hedged attempt.
            results.put((b,) + self._forward_timed(
                b, raw, rid, accept, deadline_left_ms, trace_header))

        threading.Thread(target=attempt, args=(primary,),
                         name=f"hedge-p-{rid[:8]}", daemon=True).start()
        contenders = 1
        hedged = False
        try:
            res = results.get(timeout=delay)
        except queue.Empty:
            res = None
            hedge = self._pick_hedge(tried)
            if hedge is not None:
                tried.append(hedge.bid)
                self.cluster_metrics.hedges.labels(outcome="fired").inc()
                threading.Thread(target=attempt, args=(hedge,),
                                 name=f"hedge-h-{rid[:8]}",
                                 daemon=True).start()
                contenders = 2
                hedged = True
        # A failed arrival waits for the other contender (bounded by
        # the per-attempt socket timeout each thread already carries).
        budget = self.config.request_timeout_s + 5.0
        seen: List[Tuple] = []
        while True:
            if res is None:
                if len(seen) >= contenders:
                    break
                try:
                    res = results.get(timeout=budget)
                except queue.Empty:  # pragma: no cover - defensive
                    break
            seen.append(res)
            if res[1] == "ok":
                break
            res = None
        winner = next((r for r in seen if r[1] == "ok"), None)
        if winner is None:
            winner = seen[-1] if seen else (
                primary, "timeout", 0, b"", "application/json", {})
        if hedged:
            self.cluster_metrics.hedges.labels(
                outcome=("won" if winner[1] == "ok"
                         and winner[0] is not primary else "lost")).inc()
        return winner

    def route_predict(self, raw: bytes, session_id: Optional[str],
                      rid: str, accept: Optional[str] = None,
                      deadline_ms: Optional[float] = None,
                      trace: Optional[Tuple[Optional[str],
                                            Optional[str]]] = None
                      ) -> Tuple[int, bytes, str, Dict[str, str]]:
        """Pick a backend and proxy; bounded failover for cold requests.
        Never blocks without a timeout and never retries work that may
        have executed unless it is idempotent (cold inference).
        Returns (status, body, content_type, headers).

        ``trace`` is the continued ``(trace_id, parent_span_id)`` from
        the client's X-Trace-Context (httpbase.trace_of): trace_id None
        means the client sent sampled=0 — every span this route records
        silently no-ops (obs/trace.py) and the header forwarded to the
        backend says sampled=0 too.  Default (direct callers, tests)
        keeps the pre-PR 20 behavior: rid doubles as the trace id."""
        cfg = self.config
        t0 = time.perf_counter()
        tid, t_parent = trace if trace is not None else (rid, None)
        # The route span's id is minted up front so every hop span can
        # parent under it even though the route span is recorded last.
        route_sid = self.tracer.new_span_id()
        is_session = session_id is not None
        attempts = cfg.retries + 1
        tried: List[int] = []
        detail = "no ready backend"
        spilled_shed = False
        for attempt in range(attempts):
            left_ms = None
            if deadline_ms is not None:
                left_ms = deadline_ms - (time.perf_counter() - t0) * 1e3
                if left_ms <= 0.0:
                    # The client's budget died at this hop (queueing,
                    # earlier failed attempts, backoff) — answering 504
                    # here is cheaper than letting a backend compute a
                    # disparity nobody reads.
                    self.tracer.record(
                        "route", t0, time.perf_counter(), tid,
                        parent_id=t_parent, span_id=route_sid,
                        attrs={"attempts": len(tried), "status": 504,
                               "detail": "deadline exhausted"})
                    self._tail_offer(tid, t0, 504)
                    return 504, json.dumps(
                        {"error": "timeout",
                         "detail": "deadline exhausted at the router "
                                   "hop"}).encode(), \
                        "application/json", {"X-Request-Id": rid}
            if is_session:
                backend = self._pin_backend(str(session_id),
                                            exclude=tuple(tried))
            else:
                backend = self._pick_cold(tried)
            if backend is None:
                break
            tried.append(backend.bid)
            if attempt and not spilled_shed:
                # Same exponential-backoff-with-jitter schedule as the
                # client's retries (utils/backoff.py — one formula for
                # both ends of the failover story).  A shed (healthy 503
                # reply) spills immediately instead — there is no failure
                # storm to decorrelate, and the in-process dispatcher
                # spills Overloaded replicas without a pause too.
                time.sleep(backoff_delay(cfg.retry_backoff_ms,
                                         attempt - 1))
            spilled_shed = False
            t_fwd = time.perf_counter()
            # Hop span id is minted BEFORE the forward: it leaves in the
            # X-Trace-Context header as the backend's parent, and the
            # span itself is recorded once the forward returns.
            hop_sid = self.tracer.new_span_id()
            hdr = format_trace_context(tid or rid,
                                       hop_sid if tid else None,
                                       sampled=tid is not None)
            backend, phase, status, body, ctype, headers = \
                self._forward_hedged(backend, raw, rid, accept, tried,
                                     is_session, left_ms, hdr)
            self.tracer.record(
                "router_hop", t_fwd, time.perf_counter(), tid,
                parent_id=route_sid, span_id=hop_sid,
                attrs={"backend": backend.name, "attempt": attempt,
                       "phase": phase, "status": status,
                       "session": is_session})
            if phase == "ok":
                if status == 500 and not is_session:
                    # Backend crashed mid-inference: cold inference is
                    # idempotent, fail over like a connection error.
                    self._record(backend, "failover")
                    detail = f"backend {backend.name} answered 500"
                    continue
                if status == 503 and not is_session:
                    # Backend shed (queue full / draining just started):
                    # nothing executed, so spill the cold request to the
                    # next-least-loaded backend — matching the in-process
                    # dispatcher, the cluster is only overloaded when
                    # every ready backend is.  (Session frames stay put:
                    # their pinned backend shedding is backpressure the
                    # client must pace to, not a reason to move state.)
                    self._record(backend, "shed")
                    detail = f"backend {backend.name} shed (503)"
                    spilled_shed = True
                    continue
                outcome = {200: "ok", 503: "shed",
                           504: "timeout"}.get(status, "error")
                self._record(backend, outcome)
                # Router-added latency: everything before the successful
                # forward began (route pick, failed attempts, backoffs)
                # — the backend's own compute is excluded.
                self.cluster_metrics.router_latency.observe(t_fwd - t0)
                self.tracer.record("route", t0, time.perf_counter(), tid,
                                   parent_id=t_parent, span_id=route_sid,
                                   attrs={"backend": backend.name,
                                          "attempts": attempt + 1,
                                          "status": status})
                self._tail_offer(tid, t0, status)
                return status, body, ctype, headers
            if phase == "timeout":
                # The backend may still be computing: a blind retry would
                # run inference twice AND double the client's wait.
                self._record(backend, "timeout")
                self._tail_offer(tid, t0, 504)
                return 504, json.dumps(
                    {"error": "timeout",
                     "detail": f"backend {backend.name} exceeded "
                               f"{cfg.request_timeout_s}s"}).encode(), \
                    "application/json", {"X-Request-Id": rid}
            if phase == "response" and is_session:
                # The frame may have executed; a duplicate would advance
                # the session state.  Fail clean, client decides.
                self._record(backend, "error")
                self._tail_offer(tid, t0, 503)
                return 503, json.dumps(
                    {"error": "unavailable",
                     "detail": f"backend {backend.name} failed "
                               f"mid-frame; session state unknown"}
                ).encode(), "application/json", \
                    {"X-Request-Id": rid, "Retry-After": "1"}
            # connect-phase failure (any request), or response-phase
            # failure of an idempotent cold request: fail over.
            self._record(backend, "connect_error" if phase == "connect"
                         else "failover")
            detail = f"backend {backend.name} {phase} failure"
        self.refresh_gauges()
        self.tracer.record("route", t0, time.perf_counter(), tid,
                           parent_id=t_parent, span_id=route_sid,
                           attrs={"attempts": len(tried), "status": 503,
                                  "detail": detail})
        self._tail_offer(tid, t0, 503)
        return 503, json.dumps(
            {"error": "unavailable", "detail": detail,
             "attempts": len(tried)}).encode(), "application/json", \
            {"X-Request-Id": rid, "Retry-After": "1"}

    # -------------------------------------------------- binary streaming

    def route_predict_stream(self, handler, prefix: bytes,
                             remaining: int, session_id: Optional[str],
                             rid: str,
                             accept: Optional[str] = None,
                             deadline_ms: Optional[float] = None,
                             trace: Optional[Tuple[Optional[str],
                                                   Optional[str]]] = None
                             ) -> None:
        """Forward a binary /predict without ever holding the full body.

        ``prefix`` is the already-peeked header + meta block (needed for
        session routing); ``remaining`` is how many body bytes are still
        unread on ``handler.rfile``.  The body is pumped to the chosen
        backend in ``WIRE_CHUNK`` slices and the response is relayed the
        same way, so the router's peak buffering per request stays at
        one chunk regardless of pair size — the whole point of routing a
        spatial-bucket body through a 64 KiB window.

        Failover is connect-phase only: once a single payload byte has
        been consumed from the client socket it cannot be replayed, so
        any later failure answers the client directly (503/504) after
        draining what the client is still sending, leaving keep-alive in
        a defined state.  Replies are written straight to ``handler``;
        this method returns nothing.
        """
        cfg = self.config
        t0 = time.perf_counter()
        tid, t_parent = trace if trace is not None else (rid, None)
        route_sid = self.tracer.new_span_id()
        hop_sid = ""
        is_session = session_id is not None
        attempts = cfg.retries + 1
        tried: List[int] = []
        detail = "no ready backend"
        conn = None
        backend = None
        for attempt in range(attempts):
            left_ms = None
            if deadline_ms is not None:
                left_ms = deadline_ms - (time.perf_counter() - t0) * 1e3
                if left_ms <= 0.0:
                    # The unread body is still on the client socket:
                    # drain it first so the reply lands on a keep-alive
                    # connection in a defined state.
                    self._drain_client(handler, remaining)
                    self._tail_offer(tid, t0, 504)
                    self._json_reply(
                        handler, 504,
                        {"error": "timeout",
                         "detail": "deadline exhausted at the router "
                                   "hop"},
                        {"X-Request-Id": rid})
                    return
            if is_session:
                backend = self._pin_backend(str(session_id),
                                            exclude=tuple(tried))
            else:
                backend = self._pick_cold(tried)
            if backend is None:
                break
            tried.append(backend.bid)
            if attempt:
                time.sleep(backoff_delay(cfg.retry_backoff_ms,
                                         attempt - 1))
            conn = http.client.HTTPConnection(
                backend.host, backend.port,
                timeout=cfg.request_timeout_s)
            # Same pre-minted hop-span-id discipline as the JSON path:
            # the id leaves in the header now, the span records after
            # the relay completes.
            hop_sid = self.tracer.new_span_id()
            try:
                conn.putrequest("POST", "/predict")
                conn.putheader("Content-Type", wire.WIRE_CONTENT_TYPE)
                conn.putheader("Content-Length",
                               str(len(prefix) + remaining))
                conn.putheader("X-Request-Id", rid)
                conn.putheader(TRACE_HEADER, format_trace_context(
                    tid or rid, hop_sid if tid else None,
                    sampled=tid is not None))
                if accept:
                    conn.putheader("Accept", accept)
                if left_ms is not None:
                    conn.putheader("X-Deadline-Ms",
                                   f"{max(left_ms, 1.0):.0f}")
                conn.endheaders()
                conn.send(prefix)
            except OSError:
                backend.mark_unreachable()
                backend.breaker.record_failure()
                self._record(backend, "connect_error")
                detail = f"backend {backend.name} connect failure"
                conn.close()
                conn = None
                continue
            break
        if conn is None or backend is None:
            self.refresh_gauges()
            self._tail_offer(tid, t0, 503)
            self._json_reply(handler, 503,
                             {"error": "unavailable", "detail": detail,
                              "attempts": len(tried)},
                             {"X-Request-Id": rid, "Retry-After": "1"})
            return
        # Past this point the client body starts draining; no failover.
        backend.begin()
        t_fwd = time.perf_counter()
        sent = len(prefix)
        peak = len(prefix)
        # corrupt_frame@request=N chaos hook: bit-flip ONE payload byte
        # of the next relayed frame mid-pump — wire-plane corruption
        # between hops.  The backend's FrameDecoder rejects the frame
        # (zlib/consistency failure -> WireError -> clean 400 with the
        # request id) and the reply relays like any other; the stream
        # stays length-framed so neither socket hangs.
        corrupt = self.fault_plan.corrupt_stream()
        try:
            try:
                left = remaining
                while left:
                    chunk = handler.rfile.read(min(WIRE_CHUNK, left))
                    if not chunk:
                        # Client hung up mid-body; nothing sane to reply.
                        handler.close_connection = True
                        self._record(backend, "error")
                        return
                    if corrupt:
                        corrupt = False
                        i = len(chunk) // 2
                        chunk = (chunk[:i]
                                 + bytes((chunk[i] ^ 0xFF,))
                                 + chunk[i + 1:])
                    conn.send(chunk)
                    left -= len(chunk)
                    sent += len(chunk)
                    peak = max(peak, len(chunk))
            except (socket.timeout, OSError):
                backend.mark_unreachable()
                backend.breaker.record_failure()
                self._record(backend, "error")
                self._drain_client(handler, left)
                self._tail_offer(tid, t0, 503)
                self._json_reply(
                    handler, 503,
                    {"error": "unavailable",
                     "detail": f"backend {backend.name} failed "
                               f"mid-stream"},
                    {"X-Request-Id": rid, "Retry-After": "1"})
                return
            try:
                resp = conn.getresponse()
            except socket.timeout:
                backend.breaker.record_failure()
                self._record(backend, "timeout")
                self._tail_offer(tid, t0, 504)
                self._json_reply(
                    handler, 504,
                    {"error": "timeout",
                     "detail": f"backend {backend.name} exceeded "
                               f"{cfg.request_timeout_s}s"},
                    {"X-Request-Id": rid})
                return
            except (http.client.HTTPException, OSError):
                backend.mark_unreachable()
                backend.breaker.record_failure()
                self._record(backend, "error")
                self._tail_offer(tid, t0, 503)
                self._json_reply(
                    handler, 503,
                    {"error": "unavailable",
                     "detail": f"backend {backend.name} failed "
                               f"mid-stream"},
                    {"X-Request-Id": rid, "Retry-After": "1"})
                return
            backend.breaker.record_success()
            self._record(backend, {200: "ok", 503: "shed",
                                   504: "timeout"}.get(resp.status,
                                                       "error"))
            self.cluster_metrics.router_latency.observe(t_fwd - t0)
            received = self._relay_response(handler, resp, backend, rid)
            peak = max(peak, min(received, WIRE_CHUNK))
            with self._stream_lock:
                self._stream_requests += 1
                self._stream_peak_chunk = max(self._stream_peak_chunk,
                                              peak)
                peak_seen = self._stream_peak_chunk
            m = self.cluster_metrics
            m.wire_stream_bytes.labels(direction="in").inc(sent)
            m.wire_stream_bytes.labels(direction="out").inc(received)
            m.wire_stream_peak_chunk.set(peak_seen)
            self.tracer.record(
                "router_hop", t_fwd, time.perf_counter(), tid,
                parent_id=route_sid, span_id=hop_sid,
                attrs={"backend": backend.name, "phase": "ok",
                       "status": resp.status, "stream": True})
            self.tracer.record(
                "route", t0, time.perf_counter(), tid,
                parent_id=t_parent, span_id=route_sid,
                attrs={"backend": backend.name, "attempts": len(tried),
                       "status": resp.status, "stream": True,
                       "bytes_in": sent, "bytes_out": received})
            self._tail_offer(tid, t0, resp.status)
        finally:
            backend.end()
            conn.close()

    def _relay_response(self, handler, resp, backend: Backend,
                        rid: str) -> int:
        """Relay a backend reply chunk-at-a-time; returns body bytes."""
        length = resp.headers.get("Content-Length")
        handler.send_response(resp.status)
        handler.send_header("Content-Type",
                            resp.headers.get("Content-Type",
                                             "application/json"))
        if length is not None:
            handler.send_header("Content-Length", length)
        handler.send_header("X-Request-Id",
                            resp.headers.get("X-Request-Id", rid))
        handler.send_header("X-Backend", backend.name)
        handler.end_headers()
        received = 0
        while True:
            chunk = resp.read(WIRE_CHUNK)
            if not chunk:
                break
            handler.wfile.write(chunk)
            received += len(chunk)
        return received

    @staticmethod
    def _drain_client(handler, left: int) -> None:
        """Swallow the rest of a client body after a mid-stream backend
        failure so the error reply lands on a keep-alive connection in a
        defined state (mirrors httpbase's short-read discipline)."""
        try:
            while left:
                chunk = handler.rfile.read(min(WIRE_CHUNK, left))
                if not chunk:
                    handler.close_connection = True
                    return
                left -= len(chunk)
        except OSError:
            handler.close_connection = True

    @staticmethod
    def _json_reply(handler, status: int, obj: Dict,
                    headers: Dict[str, str]) -> None:
        """Router-originated error reply (errors are ALWAYS JSON —
        docs/wire_format.md negotiation matrix)."""
        body = json.dumps(obj).encode()
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            handler.send_header(k, v)
        handler.end_headers()
        handler.wfile.write(body)

    def stream_stats(self) -> Dict[str, int]:
        """Instrumentation for the no-full-buffering assertion: the
        largest single buffer the streaming path ever held is
        ``peak_chunk_bytes`` — tests pin it to ``WIRE_CHUNK`` while
        pushing spatial-bucket-sized bodies through."""
        with self._stream_lock:
            return {"requests": self._stream_requests,
                    "peak_chunk_bytes": self._stream_peak_chunk}


def build_router(config: RouterConfig,
                 tracer: Optional[Tracer] = None) -> StereoRouter:
    """Construct + start a router (first probe already done, prober
    running).  The caller drives ``serve_forever()`` and ``close()``."""
    router = StereoRouter(config, tracer=tracer).start()
    logger.info("routing on %s:%d over %s", config.host, router.port,
                [f"{h}:{p}" for h, p in config.backends])
    return router
