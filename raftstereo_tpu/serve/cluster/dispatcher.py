"""Cluster dispatcher: one admission surface over N engine replicas.

The dispatcher is the in-process seam between the HTTP layer and a
:class:`~raftstereo_tpu.serve.cluster.replica.ReplicaSet`: it quacks
like the component it replaces (``DynamicBatcher.submit`` /
``IterationScheduler.submit`` for plain requests, ``StreamRunner.step``
for session frames), so ``StereoServer`` routes through it unchanged.

Placement policy:

* **cold requests** go to the READY replica with the least outstanding
  work (queued + in flight).  A replica that sheds (``Overloaded``)
  spills to the next-least-loaded one — the cluster is only overloaded
  when every ready replica is;
* **session frames are sticky**: RAFT's warm-start state (the previous
  frame's low-res disparity) lives in the pinned replica's session
  store, so moving a session means losing its state.  A frame re-pins
  only when its replica is gone (failed/draining) — the new replica
  serves it as a cold frame, never an error (the PR 3 contract), and
  ``cluster_session_repins_total`` counts it;
* **scheduled jobs stay put**: a request that joined a replica's running
  batch completes there; the dispatcher never migrates device-resident
  carried state.

Results are annotated with ``replica=<name>`` (via a chained future, so
the name is set before any ``result()`` waiter can observe the value) —
the session-stickiness and placement tests read it off the wire.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from ...config import ServeConfig
from ..batcher import Future, Overloaded, RequestTimedOut, ShuttingDown
from ..metrics import ClusterMetrics, ServeMetrics
from .pins import PinTable
from .replica import Replica, ReplicaSet

__all__ = ["ClusterDispatcher"]


def _outcome_of(exc: Optional[BaseException]) -> str:
    if exc is None:
        return "ok"
    if isinstance(exc, Overloaded):
        return "shed"
    if isinstance(exc, RequestTimedOut):
        return "timeout"
    if isinstance(exc, ShuttingDown):
        return "unavailable"
    return "error"


class _StoreView:
    """``len()``-able view over every replica's session store (what the
    /healthz stream block reports for the whole cluster)."""

    def __init__(self, replicas):
        self._replicas = replicas

    def __len__(self) -> int:
        return sum(len(r.stream.store) for r in self._replicas
                   if r.stream is not None)


class ClusterDispatcher:
    """Thread-safe placement layer over a ReplicaSet."""

    def __init__(self, replicaset: ReplicaSet, config: ServeConfig,
                 metrics: Optional[ServeMetrics] = None, tracer=None):
        self.rset = replicaset
        self.cfg = config
        self.metrics = metrics or replicaset.metrics
        # Autoscaling families live on the SAME registry as the serve
        # bundle: one /metrics scrape covers both.
        self.cluster_metrics = ClusterMetrics(self.metrics.registry)
        self.tracer = tracer
        self._lock = threading.Lock()
        # session_id -> replica rid (LRU-bounded; an evicted pin behaves
        # exactly like a lost session: next frame re-pins and runs cold).
        self._pins = PinTable(self.rset.cluster_cfg.session_pin_limit)
        self._closed = False  # guarded_by: _lock

    # ----------------------------------------------------------- placement

    def _candidates(self):
        """Ready replicas, least outstanding work first."""
        return sorted(self.rset.ready_replicas(),
                      key=lambda r: (r.outstanding(), r.rid))

    def _record(self, replica_name: str, outcome: str) -> None:
        self.cluster_metrics.dispatch.labels(
            replica=replica_name, outcome=outcome).inc()

    def _track(self, replica: Replica, inner: Future,
               trace_id: Optional[str]) -> Future:
        """Chain an outer future that (1) annotates the result with the
        answering replica, (2) settles the replica's inflight/error
        accounting, (3) labels the dispatch outcome — all before the
        outer future resolves, so readers never see a half-annotated
        result."""
        replica.begin_dispatch()
        outer = Future()

        def settle(f: Future) -> None:
            exc = f._exc
            outcome = _outcome_of(exc)
            # Engine failures count toward fail_threshold; backpressure
            # (shed/timeout/shutdown) does not — an overloaded replica
            # is healthy.
            replica.end_dispatch(ok=outcome != "error")
            self._record(replica.name, outcome)
            value = f._value
            if value is not None:
                value.replica = replica.name
            self._refresh_gauges()
            outer._resolve(value=value, exc=exc)

        inner.add_done_callback(settle)
        return outer

    def _refresh_gauges(self) -> None:
        cm = self.cluster_metrics
        cm.set_states(self.rset.states())
        ready = []
        for r in self.rset.replicas:
            out = r.outstanding()
            cm.queue_depth.labels(replica=r.name).set(out)
            if r.routable():
                ready.append(out)
        cap = max(1, self.cfg.max_batch_size)
        cm.utilization.set(
            round(sum(min(1.0, o / cap) for o in ready) / len(ready), 4)
            if ready else 0.0)
        # Re-export the scalar serve/sched gauges as cluster-wide
        # aggregates of the per-replica private instruments — N replica
        # workers writing one shared sample would be last-writer-wins
        # noise (see replica._ReplicaMetricsView).
        reps = self.rset.replicas
        sm = self.metrics
        sm.queue_depth.set(sum(r.metrics.queue_depth.value for r in reps))
        if self.cfg.sched is not None:
            sm.sched_slots_active.set(
                sum(r.metrics.sched_slots_active.value for r in reps))
            sm.sched_occupancy.set(round(
                sum(r.metrics.sched_occupancy.value for r in reps)
                / len(reps), 4))
            by_prio: Dict[str, float] = {}
            for r in reps:
                for labels, child in r.metrics.sched_queue_depth.series():
                    by_prio[labels[0]] = by_prio.get(labels[0], 0.0) \
                        + child.value
            for prio, depth in by_prio.items():
                sm.sched_queue_depth.labels(priority=prio).set(depth)

    # ------------------------------------------------------------ admission

    @property
    def queue_depth(self) -> int:
        """Cluster-wide outstanding work (the /healthz queue signal)."""
        return sum(r.outstanding() for r in self.rset.replicas)

    @property
    def store(self) -> _StoreView:
        return _StoreView(self.rset.replicas)

    def stats(self) -> Dict[str, object]:
        info = self.rset.stats()
        info["session_pins"] = len(self._pins)
        info["queue_depth"] = self.queue_depth
        if self.cfg.sched is not None:
            # The scheduler-mode healthz block: aggregate the per-replica
            # scheduler snapshots under the usual keys.
            scheds = [r.scheduler.stats() for r in self.rset.replicas]
            info["iters_per_step"] = self.cfg.sched.iters_per_step
            info["active_slots"] = sum(s["active_slots"] for s in scheds)
            by_prio: Dict[str, int] = {}
            for s in scheds:
                for p, n in s["queue_depth_by_priority"].items():
                    by_prio[p] = by_prio.get(p, 0) + n
            info["queue_depth_by_priority"] = by_prio
        return info

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               iters: Optional[int] = None, *,
               priority: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               mode: Optional[str] = None) -> Future:
        """Place one cold request on the least-loaded ready replica;
        spills to the next one when a replica sheds.  Signature covers
        both backend modes — ``priority``/``deadline_ms`` are only legal
        under ``--sched`` (the HTTP layer already enforces that);
        ``mode`` (the resolved accuracy tier, ops/quant.py) is forwarded
        verbatim — every replica warms the same tier set, so placement is
        tier-blind."""
        with self._lock:
            if self._closed:
                raise ShuttingDown("cluster dispatcher stopped")
        t0 = time.perf_counter()
        last_exc: Optional[Exception] = None
        candidates = self._candidates()
        if not candidates:
            self._refresh_gauges()
            raise ShuttingDown("no ready replica")
        for replica in candidates:
            try:
                if replica.scheduler is not None:
                    inner = replica.scheduler.submit(
                        image1, image2, iters=iters, priority=priority,
                        deadline_ms=deadline_ms, trace_id=trace_id,
                        mode=mode)
                else:
                    inner = replica.batcher.submit(
                        image1, image2, iters, trace_id=trace_id,
                        mode=mode)
            except Overloaded as e:
                self._record(replica.name, "shed")
                last_exc = e
                continue
            except ShuttingDown as e:
                last_exc = e
                continue
            if self.tracer is not None and trace_id is not None:
                self.tracer.record(
                    "cluster_dispatch", t0, time.perf_counter(), trace_id,
                    attrs={"replica": replica.name,
                           "outstanding": replica.outstanding()})
            return self._track(replica, inner, trace_id)
        self._refresh_gauges()
        raise last_exc if last_exc is not None else Overloaded(
            "every ready replica is overloaded")

    # -------------------------------------------------------------- streams

    def _pin(self, session_id: str) -> Replica:
        """Sticky replica for a session, (re)pinning as needed (one
        atomic decision inside the shared PinTable)."""
        with self._lock:
            if self._closed:
                raise ShuttingDown("cluster dispatcher stopped")
        rid, repinned = self._pins.pin(
            session_id,
            still_ok=lambda r: self.rset.replicas[r].routable(),
            choose=lambda: (lambda c: c[0].rid if c else None)(
                self._candidates()))
        if rid is None:
            raise ShuttingDown(
                f"no ready replica for session {session_id!r}")
        if repinned:
            self.cluster_metrics.session_repins.inc()
        return self.rset.replicas[rid]

    def step(self, session_id: str, seq_no: Optional[int],
             left: np.ndarray, right: np.ndarray,
             trace_id: Optional[str] = None,
             mode: Optional[str] = None):
        """One session frame through its pinned replica (StreamRunner
        contract).  Raises the batcher exception types on backpressure,
        which the HTTP layer already maps to 503/504."""
        replica = self._pin(session_id)
        t0 = time.perf_counter()
        if self.tracer is not None and trace_id is not None:
            self.tracer.record("cluster_dispatch", t0, t0, trace_id,
                               attrs={"replica": replica.name,
                                      "session_id": session_id,
                                      "sticky": True})
        replica.begin_dispatch()
        try:
            res = replica.stream.step(session_id, seq_no, left, right,
                                      trace_id=trace_id, mode=mode)
        except (Overloaded, RequestTimedOut, ShuttingDown) as e:
            replica.end_dispatch(ok=True)  # backpressure, not a failure
            self._record(replica.name, _outcome_of(e))
            raise
        except Exception:
            replica.end_dispatch(ok=False)
            self._record(replica.name, "error")
            raise
        replica.end_dispatch(ok=True)
        self._record(replica.name, "ok")
        res.replica = replica.name
        self._refresh_gauges()
        return res

    # ------------------------------------------------------------ lifecycle

    def drain(self) -> None:
        """Stop admitting on every replica; admitted work finishes."""
        for r in self.rset.replicas:
            r.drain()
        self._refresh_gauges()

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        with self._lock:
            self._closed = True
        self.rset.stop(drain=drain)
