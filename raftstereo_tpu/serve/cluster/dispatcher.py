"""Cluster dispatcher: one admission surface over N engine replicas.

The dispatcher is the in-process seam between the HTTP layer and a
:class:`~raftstereo_tpu.serve.cluster.replica.ReplicaSet`: it quacks
like the component it replaces (``DynamicBatcher.submit`` /
``IterationScheduler.submit`` for plain requests, ``StreamRunner.step``
for session frames), so ``StereoServer`` routes through it unchanged.

Placement policy:

* **cold requests** go to the READY replica with the least outstanding
  work (queued + in flight).  A replica that sheds (``Overloaded``)
  spills to the next-least-loaded one — the cluster is only overloaded
  when every ready replica is;
* **session frames are sticky**: RAFT's warm-start state (the previous
  frame's low-res disparity + controller EMA) lives in the pinned
  replica's session store.  A frame re-pins only when its replica is
  unusable (failed/draining/pin evicted) — and since PR 13 the re-pin
  performs a replica-to-replica WARM HANDOFF first: the old home's state
  is exported (``SessionStore.export_state``) and imported into the new
  one, so the next frame runs warm whenever the engines' state-schema
  fingerprints agree.  ``cluster_session_repins_total{reason=}`` counts
  why the pin moved and ``cluster_session_handoffs_total{outcome=}``
  whether the warmth survived (warm / cold_schema / cold_lost — cold is
  a documented fallback, never an error, the PR 3 contract);
* **drain migrates proactively**: ``drain_replica`` (the rolling-restart
  verb behind ``POST /debug/restart``) exports every live session off
  the draining replica and re-pins it warm BEFORE the next frame
  arrives, so a planned restart costs zero cold frames;
* **scheduled jobs stay put**: a request that joined a replica's running
  batch completes there; the dispatcher never migrates device-resident
  carried state.

Results are annotated with ``replica=<name>`` (via a chained future, so
the name is set before any ``result()`` waiter can observe the value) —
the session-stickiness and placement tests read it off the wire.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from ...config import ServeConfig
from ...ops.autoscale import Autoscaler, load_capacity_model
from ..batcher import Future, Overloaded, RequestTimedOut, ShuttingDown
from ..metrics import ClusterMetrics, ServeMetrics
from .pins import PinTable
from .replica import Replica, ReplicaSet

__all__ = ["ClusterDispatcher"]


def _outcome_of(exc: Optional[BaseException]) -> str:
    if exc is None:
        return "ok"
    if isinstance(exc, Overloaded):
        return "shed"
    if isinstance(exc, RequestTimedOut):
        return "timeout"
    if isinstance(exc, ShuttingDown):
        return "unavailable"
    return "error"


class _StoreView:
    """``len()``-able view over every replica's session store (what the
    /healthz stream block reports for the whole cluster)."""

    def __init__(self, replicas):
        self._replicas = replicas

    def __len__(self) -> int:
        return sum(len(r.stream.store) for r in self._replicas
                   if r.stream is not None)

    def total_bytes(self) -> int:
        """Accounted session-state bytes across every replica's store
        (the cluster-wide ``stream_session_bytes`` value)."""
        return sum(r.stream.store.total_bytes() for r in self._replicas
                   if r.stream is not None)

    def session_ids(self):
        """Live session ids across every replica (the tier publisher's
        re-attach resync sweep iterates this)."""
        sids = []
        for r in self._replicas:
            if r.stream is not None:
                sids.extend(r.stream.store.session_ids())
        return sids


class ClusterDispatcher:
    """Thread-safe placement layer over a ReplicaSet."""

    def __init__(self, replicaset: ReplicaSet, config: ServeConfig,
                 metrics: Optional[ServeMetrics] = None, tracer=None):
        self.rset = replicaset
        self.cfg = config
        self.metrics = metrics or replicaset.metrics
        # Autoscaling families live on the SAME registry as the serve
        # bundle: one /metrics scrape covers both.
        self.cluster_metrics = ClusterMetrics(self.metrics.registry)
        self.tracer = tracer
        self._lock = threading.Lock()
        # session_id -> replica rid (LRU-bounded; an evicted pin behaves
        # exactly like a lost session: next frame re-pins and runs cold).
        self._pins = PinTable(self.rset.cluster_cfg.session_pin_limit)
        self._closed = False  # guarded_by: _lock
        # Export-in-flight markers: one migration per session at a time
        # (a per-frame re-pin handoff racing a drain-time sweep would
        # export the same state twice for nothing — the store's monotonic
        # import guard makes the race safe, the marker makes it cheap).
        self._migrate_lock = threading.Lock()
        self._migrating = set()  # guarded_by: _migrate_lock
        ccfg = self.rset.cluster_cfg
        capacity = (load_capacity_model(ccfg.capacity_model)
                    if ccfg.capacity_model else None)
        self._autoscaler = Autoscaler(capacity=capacity,
                                      target_rps=ccfg.target_rps)
        self._advice: Dict[str, object] = {}

    # ----------------------------------------------------------- placement

    def _candidates(self):
        """Ready replicas, least outstanding work first."""
        return sorted(self.rset.ready_replicas(),
                      key=lambda r: (r.outstanding(), r.rid))

    def _record(self, replica_name: str, outcome: str) -> None:
        self.cluster_metrics.dispatch.labels(
            replica=replica_name, outcome=outcome).inc()

    def _track(self, replica: Replica, inner: Future,
               trace_id: Optional[str]) -> Future:
        """Chain an outer future that (1) annotates the result with the
        answering replica, (2) settles the replica's inflight/error
        accounting, (3) labels the dispatch outcome — all before the
        outer future resolves, so readers never see a half-annotated
        result."""
        replica.begin_dispatch()
        outer = Future()

        def settle(f: Future) -> None:
            exc = f._exc
            outcome = _outcome_of(exc)
            # Engine failures count toward fail_threshold; backpressure
            # (shed/timeout/shutdown) does not — an overloaded replica
            # is healthy.
            replica.end_dispatch(ok=outcome != "error")
            self._record(replica.name, outcome)
            value = f._value
            if value is not None:
                value.replica = replica.name
            self._refresh_gauges()
            outer._resolve(value=value, exc=exc)

        inner.add_done_callback(settle)
        return outer

    def _refresh_gauges(self) -> None:
        cm = self.cluster_metrics
        cm.set_states(self.rset.states())
        ready = []
        for r in self.rset.replicas:
            out = r.outstanding()
            cm.queue_depth.labels(replica=r.name).set(out)
            if r.routable():
                ready.append(out)
        cap = max(1, self.cfg.max_batch_size)
        cm.utilization.set(
            round(sum(min(1.0, o / cap) for o in ready) / len(ready), 4)
            if ready else 0.0)
        # Re-export the scalar serve/sched gauges as cluster-wide
        # aggregates of the per-replica private instruments — N replica
        # workers writing one shared sample would be last-writer-wins
        # noise (see replica._ReplicaMetricsView).
        reps = self.rset.replicas
        sm = self.metrics
        sm.queue_depth.set(sum(r.metrics.queue_depth.value for r in reps))
        if self.cfg.sched is not None:
            sm.sched_slots_active.set(
                sum(r.metrics.sched_slots_active.value for r in reps))
            sm.sched_occupancy.set(round(
                sum(r.metrics.sched_occupancy.value for r in reps)
                / len(reps), 4))
            by_prio: Dict[str, float] = {}
            for r in reps:
                for labels, child in r.metrics.sched_queue_depth.series():
                    by_prio[labels[0]] = by_prio.get(labels[0], 0.0) \
                        + child.value
            for prio, depth in by_prio.items():
                sm.sched_queue_depth.labels(priority=prio).set(depth)
        # Feed the landed autoscaling signals through the recommendation
        # loop (ops/autoscale.py) — advice surfaces in /debug/vars and
        # the cluster_autoscale_recommendation gauge.
        shed = sum(child.value for labels, child in cm.dispatch.series()
                   if labels[1] == "shed")
        # Session-memory pressure: accounted state bytes over the
        # fleet's configured byte budget (stream/session.py).  0.0 when
        # streaming is off or no budget is set — the scale signal only
        # engages where eviction pressure is a real possibility.
        memory_pressure = 0.0
        scfg = self.cfg.stream
        if scfg is not None and scfg.session_budget_mb > 0:
            stores = [r for r in self.rset.replicas if r.stream is not None]
            if stores:
                budget = scfg.session_budget_mb * 2 ** 20 * len(stores)
                memory_pressure = round(
                    self.store.total_bytes() / budget, 4)
        advice = self._autoscaler.observe(
            ready=len(ready), utilization=cm.utilization.value,
            occupancy=(sm.sched_occupancy.value
                       if self.cfg.sched is not None else None),
            shed_total=shed, memory_pressure=memory_pressure)
        cm.autoscale_recommendation.set(advice["delta"])
        cap = advice.get("capacity")
        # 0.0 without a model: the gauge renders from startup either
        # way, and "no model" and "no headroom information" read the
        # same to an alerting rule (documented in docs/serving.md).
        cm.capacity_headroom.set(cap["headroom"] if cap else 0.0)
        self._advice = advice

    # ------------------------------------------------------------ admission

    @property
    def queue_depth(self) -> int:
        """Cluster-wide outstanding work (the /healthz queue signal)."""
        return sum(r.outstanding() for r in self.rset.replicas)

    @property
    def store(self) -> _StoreView:
        return _StoreView(self.rset.replicas)

    def stats(self) -> Dict[str, object]:
        info = self.rset.stats()
        info["session_pins"] = len(self._pins)
        info["queue_depth"] = self.queue_depth
        if self._advice:
            info["autoscale"] = self._advice
        if self.cfg.sched is not None:
            # The scheduler-mode healthz block: aggregate the per-replica
            # scheduler snapshots under the usual keys.
            scheds = [r.scheduler.stats() for r in self.rset.replicas]
            info["iters_per_step"] = self.cfg.sched.iters_per_step
            info["active_slots"] = sum(s["active_slots"] for s in scheds)
            by_prio: Dict[str, int] = {}
            for s in scheds:
                for p, n in s["queue_depth_by_priority"].items():
                    by_prio[p] = by_prio.get(p, 0) + n
            info["queue_depth_by_priority"] = by_prio
        return info

    def submit(self, image1: np.ndarray, image2: np.ndarray,
               iters: Optional[int] = None, *,
               priority: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               mode: Optional[str] = None) -> Future:
        """Place one cold request on the least-loaded ready replica;
        spills to the next one when a replica sheds.  Signature covers
        both backend modes — ``priority``/``deadline_ms`` are only legal
        under ``--sched`` (the HTTP layer already enforces that);
        ``mode`` (the resolved accuracy tier, ops/quant.py) is forwarded
        verbatim — every replica warms the same tier set, so placement is
        tier-blind."""
        with self._lock:
            if self._closed:
                raise ShuttingDown("cluster dispatcher stopped")
        t0 = time.perf_counter()
        last_exc: Optional[Exception] = None
        candidates = self._candidates()
        if not candidates:
            self._refresh_gauges()
            raise ShuttingDown("no ready replica")
        for replica in candidates:
            try:
                if replica.scheduler is not None:
                    inner = replica.scheduler.submit(
                        image1, image2, iters=iters, priority=priority,
                        deadline_ms=deadline_ms, trace_id=trace_id,
                        mode=mode)
                else:
                    inner = replica.batcher.submit(
                        image1, image2, iters, trace_id=trace_id,
                        mode=mode)
            except Overloaded as e:
                self._record(replica.name, "shed")
                last_exc = e
                continue
            except ShuttingDown as e:
                last_exc = e
                continue
            if self.tracer is not None and trace_id is not None:
                self.tracer.record(
                    "cluster_dispatch", t0, time.perf_counter(), trace_id,
                    attrs={"replica": replica.name,
                           "outstanding": replica.outstanding()})
            return self._track(replica, inner, trace_id)
        self._refresh_gauges()
        raise last_exc if last_exc is not None else Overloaded(
            "every ready replica is overloaded")

    # -------------------------------------------------------------- streams

    def _pin(self, session_id: str) -> Replica:
        """Sticky replica for a session, (re)pinning as needed (one
        atomic decision inside the shared PinTable).  A re-pin attempts
        the warm handoff from the old home before the frame runs — this
        is how a frame arriving inside the drain window (replica marked
        draining, sweep not there yet) still gets its state: the export
        serializes on the session lock, so it sees the last completed
        frame."""
        with self._lock:
            if self._closed:
                raise ShuttingDown("cluster dispatcher stopped")
        rid, repinned, old = self._pins.pin(
            session_id,
            still_ok=lambda r: self.rset.replicas[r].routable(),
            choose=lambda: (lambda c: c[0].rid if c else None)(
                self._candidates()))
        if rid is None:
            raise ShuttingDown(
                f"no ready replica for session {session_id!r}")
        if repinned:
            self.cluster_metrics.session_repins.labels(
                reason=self._repin_reason(old)).inc()
            self._handoff(session_id, old, rid)
        return self.rset.replicas[rid]

    def _repin_reason(self, old_rid: Optional[int]) -> str:
        """Why the old pin was unusable (the repins metric label)."""
        if old_rid is None:
            return "evicted"
        state = self.rset.replicas[old_rid].state
        if state in ("draining", "drained"):
            return "draining"
        if state == "failed":
            return "failed"
        return "evicted"

    # ------------------------------------------------------------ migration

    def _handoff(self, session_id: str, src_rid: Optional[int],
                 dst_rid: int) -> Optional[str]:
        """Move one session's warm-start state ``src -> dst``; returns the
        counted outcome, or None when the move was a no-op (same replica,
        unknown source, or another thread already migrating this
        session).  Never raises and performs no device work — migration
        is pure host numpy, invisible to the retrace guard."""
        if src_rid is None or src_rid == dst_rid:
            return None
        with self._migrate_lock:
            if session_id in self._migrating:
                return None
            self._migrating.add(session_id)
        try:
            outcome = self._transfer(session_id,
                                     self.rset.replicas[src_rid],
                                     self.rset.replicas[dst_rid])
        finally:
            with self._migrate_lock:
                self._migrating.discard(session_id)
        self.cluster_metrics.session_handoffs.labels(
            outcome=outcome).inc()
        return outcome

    @staticmethod
    def _transfer(session_id: str, src: Replica, dst: Replica) -> str:
        """Export from ``src``, import into ``dst`` (both sides are
        StreamRunner seams; a replica without one — or without anything
        warm to export — is the cold_lost fallback)."""
        exporter = getattr(src.stream, "export_session", None) \
            if src.stream is not None else None
        importer = getattr(dst.stream, "import_session", None) \
            if dst.stream is not None else None
        if exporter is None or importer is None:
            return "cold_lost"
        snapshot = exporter(session_id)
        if snapshot is None:
            return "cold_lost"
        return importer(snapshot)

    def drain_replica(self, rid: int) -> Dict[str, object]:
        """Drain ONE replica and migrate its live sessions to the
        remaining ready replicas — the rolling-restart verb.  State moves
        BEFORE the pins do, so each migrated session's next frame runs
        warm on its new home; a frame that races the sweep takes the
        re-pin handoff path instead and ends up identical (the store's
        monotonic import guard keeps whichever state is fresher)."""
        src = self.rset.replicas[rid]
        src.drain()
        self._refresh_gauges()
        outcomes: Dict[str, str] = {}
        cands = [r for r in self._candidates() if r.rid != rid]
        if not cands:
            return {"replica": src.name, "migrated": outcomes,
                    "note": "no ready replica to migrate to"}
        # Pinned sessions plus any state-only stragglers whose pin was
        # LRU-evicted while their warmth survived in the store.
        worklist = list(dict.fromkeys(
            self._pins.pinned_to(rid)
            + (src.stream.store.session_ids()
               if src.stream is not None
               and hasattr(src.stream, "store") else [])))
        for i, sid in enumerate(worklist):
            dst = cands[i % len(cands)]
            outcome = self._handoff(sid, rid, dst.rid)
            if outcome is None:
                continue  # raced a per-frame handoff; that path counted
            outcomes[sid] = outcome
            cur = self._pins.peek(sid)
            if cur in (rid, None):
                # CAS: a concurrent pin() decision wins over the sweep.
                self._pins.reassign(sid, cur, dst.rid)
        self._refresh_gauges()
        return {"replica": src.name, "migrated": outcomes}

    def export_session(self, session_id: str) -> Optional[Dict]:
        """Wire-level export (GET /debug/sessions/<id>): the pinned
        replica's snapshot, falling back to scanning every replica (the
        pin may be gone while the state survives)."""
        order = []
        pinned = self._pins.peek(session_id)
        if pinned is not None:
            order.append(self.rset.replicas[pinned])
        order.extend(r for r in self.rset.replicas
                     if pinned is None or r.rid != pinned)
        for r in order:
            exporter = getattr(r.stream, "export_session", None) \
                if r.stream is not None else None
            if exporter is None:
                continue
            snapshot = exporter(session_id)
            if snapshot is not None:
                return snapshot
        return None

    def import_session(self, snapshot: Dict) -> str:
        """Wire-level import (POST /debug/sessions): install into the
        session's pinned replica when it is routable, else the
        least-loaded ready one (pinning it there on success) — counted
        like any other handoff."""
        sid = str(snapshot.get("session_id", ""))
        rid = self._pins.peek(sid)
        if rid is not None and self.rset.replicas[rid].routable():
            replica = self.rset.replicas[rid]
        else:
            cands = self._candidates()
            replica = cands[0] if cands else None
        importer = getattr(replica.stream, "import_session", None) \
            if replica is not None and replica.stream is not None else None
        if importer is None:
            outcome = "cold_lost"
        else:
            outcome = importer(snapshot)
            if outcome == "warm" and replica.rid != rid:
                cur = self._pins.peek(sid)
                if cur in (rid, None):
                    self._pins.reassign(sid, cur, replica.rid)
        self.cluster_metrics.session_handoffs.labels(
            outcome=outcome).inc()
        return outcome

    def evict_all(self) -> int:
        """Drop every live session on every replica (the
        ``evict_sessions`` chaos hook — StreamRunner contract).  Pins
        are left alone: a pin without state just routes the session's
        next frame to its old home, where it re-anchors cold."""
        dropped = 0
        for r in self.rset.replicas:
            evictor = (getattr(r.stream, "evict_all", None)
                       if r.stream is not None else None)
            if evictor is not None:
                dropped += evictor()
        return dropped

    def step(self, session_id: str, seq_no: Optional[int],
             left: np.ndarray, right: np.ndarray,
             trace_id: Optional[str] = None,
             mode: Optional[str] = None):
        """One session frame through its pinned replica (StreamRunner
        contract).  Raises the batcher exception types on backpressure,
        which the HTTP layer already maps to 503/504."""
        replica = self._pin(session_id)
        t0 = time.perf_counter()
        if self.tracer is not None and trace_id is not None:
            self.tracer.record("cluster_dispatch", t0, t0, trace_id,
                               attrs={"replica": replica.name,
                                      "session_id": session_id,
                                      "sticky": True})
        replica.begin_dispatch()
        try:
            res = replica.stream.step(session_id, seq_no, left, right,
                                      trace_id=trace_id, mode=mode)
        except (Overloaded, RequestTimedOut, ShuttingDown) as e:
            replica.end_dispatch(ok=True)  # backpressure, not a failure
            self._record(replica.name, _outcome_of(e))
            raise
        except Exception:
            replica.end_dispatch(ok=False)
            self._record(replica.name, "error")
            raise
        replica.end_dispatch(ok=True)
        self._record(replica.name, "ok")
        res.replica = replica.name
        self._refresh_gauges()
        return res

    # ------------------------------------------------------------ lifecycle

    def drain(self) -> None:
        """Stop admitting on every replica; admitted work finishes."""
        for r in self.rset.replicas:
            r.drain()
        self._refresh_gauges()

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        with self._lock:
            self._closed = True
        self.rset.stop(drain=drain)
