"""Serving-side surface of spatial sharding (docs/serving.md "Spatial
sharding").

The numerics live in ``parallel/spatial.py`` (the shard_map forward with
explicit halo exchange) and the executables in ``serve/engine.py``
(``infer_spatial`` / ``warmup_spatial``).  This package owns everything
the HTTP layer needs on top of that: the ``/healthz`` capability block a
client discovers the path through, and the admission policy that turns
every unsupported combination into a clean 400 *before* anything could
compile.  The one rule both halves enforce: a spatial request either
runs on an already-warmed sharded executable or it is refused — the
single largest compile in the system never happens under traffic.
"""

from .admission import (SPATIAL_ENDPOINT, admit_spatial, capability,
                        route_spatial, spatial_iters_allowed)

__all__ = ["SPATIAL_ENDPOINT", "admit_spatial", "capability",
           "route_spatial", "spatial_iters_allowed"]
