"""Admission + capability negotiation for the spatial path.

A spatial request is one pair that owns the whole (1, N) mesh for its
dispatch, so the policy here is deliberately narrow (v1):

* **Routing** is explicit-first: ``"spatial": true`` in the body forces
  the path, ``false`` forbids it, absent means *auto* — a pair whose
  longest side exceeds ``max_image_dim`` (the single-chip bucket
  ceiling) routes spatial when the server offers it, and 400s exactly
  as before when it does not.
* **No silent combinations.**  Accuracy tiers, streaming sessions and
  the iteration scheduler's ``deadline_ms``/``priority`` fields are all
  refused with a 400 naming the v1 limitation — never quietly ignored,
  never served by an uncertified or uncompiled program.
* **Never a compile.**  Unless the operator opted into
  ``cold_buckets``, a spatial request must land on a bucket
  ``warmup_spatial`` already compiled; anything else is a 400 pointing
  at ``--spatial_buckets``.  The sharded 4K executable is the most
  expensive compile in the system — admission exists so it only ever
  happens at warmup.

Everything raises plain ``ValueError`` (the server's 400 currency);
``parallel.spatial.SpatialShardingUnsupported`` is a ``ValueError``
subclass, so config-level refusals surface through the same funnel.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

# The /predict endpoint label spatial requests are counted and traced
# under (serve_requests_total{endpoint=}, the admission/dispatch spans).
SPATIAL_ENDPOINT = "spatial"


def spatial_iters_allowed(config) -> Tuple[int, ...]:
    """The iteration levels served on the spatial path — exactly the
    levels ``warmup_spatial`` compiles (v1: the primary level only; the
    degraded level is a load-shedding device for the batcher, and the
    spatial path has no queue to shed from)."""
    return (config.iters,)


def route_spatial(explicit, shape: Sequence[int], config, engine) -> bool:
    """Decide whether an admitted request runs spatially.

    ``explicit`` is the raw ``"spatial"`` body field: ``True`` demands
    the path (ValueError when the server does not offer it), ``False``
    forbids it (the plain path's ``max_image_dim`` check then applies
    unchanged), ``None``/absent auto-routes oversized pairs.
    """
    if explicit is not None and not isinstance(explicit, bool):
        raise ValueError(
            f"spatial must be a JSON boolean, got {explicit!r}")
    offered = getattr(engine, "spatial_shards", 1) > 1
    if explicit is True:
        if not offered:
            raise ValueError(
                "spatial sharding not offered by this server (start with "
                "--spatial_shards N and --spatial_buckets)")
        return True
    if explicit is False:
        return False
    return offered and max(shape[0], shape[1]) > config.max_image_dim


def admit_spatial(config, engine, iters: Optional[int],
                  accuracy, session_id, deadline_ms, priority,
                  shape: Sequence[int]) -> Tuple[Tuple[int, int], int]:
    """Validate one spatial-routed request; returns the padded
    ``(bucket_hw, iters)`` it will execute at.  Raises ``ValueError``
    (-> HTTP 400) on every v1 limitation — see the module docstring."""
    if accuracy is not None:
        raise ValueError(
            "accuracy tiers are not served on the spatial path (v1): the "
            "sharded program is certified only at the base precision — "
            "drop the accuracy field or the spatial flag")
    if session_id is not None:
        raise ValueError(
            "streaming sessions are not served on the spatial path (v1): "
            "session warm-start state lives on the single-chip bucket "
            "grid — send the frame without session_id")
    if deadline_ms is not None or priority is not None:
        raise ValueError(
            "deadline_ms/priority are scheduler features; the spatial "
            "path bypasses the iteration scheduler (v1) and runs the "
            "full iteration count")
    allowed = spatial_iters_allowed(config)
    if iters is None:
        iters = allowed[0]
    else:
        iters = int(iters)
        if iters not in allowed:
            raise ValueError(
                f"iters {iters} not served spatially; choose from "
                f"{sorted(allowed)} (only warmed levels run on the mesh)")
    hw = engine.spatial_bucket_of(shape)
    if not config.cold_buckets and not engine.is_spatial_warm(hw, iters):
        raise ValueError(
            f"shape {tuple(shape[:2])} -> spatial bucket {hw} not warmed; "
            f"configure it in --spatial_buckets (the sharded executable "
            f"is never compiled under traffic)")
    return hw, iters


def capability(config, engine) -> Dict[str, object]:
    """The ``/healthz`` ``spatial`` block — everything a client needs to
    decide whether (and at what shapes) this server can take an
    oversized pair: the shard count, the warmed buckets as PADDED
    execution shapes, the slab row alignment, the served iteration
    levels, and the body cap the buckets were sized against."""
    from ...parallel.spatial import spatial_row_multiple

    rows = (spatial_row_multiple(engine.model.config)
            if engine.model is not None else 0)
    return {
        "shards": engine.spatial_shards,
        "buckets": sorted(
            list(engine.spatial_bucket_of((h, w, engine.input_channels)))
            for h, w in getattr(config, "spatial_buckets", ()) or ()),
        "row_multiple": rows * engine.spatial_shards,
        "iters": sorted(spatial_iters_allowed(config)),
        "max_body_mb": config.max_body_mb,
    }
