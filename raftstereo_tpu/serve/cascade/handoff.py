"""The cross-tier state-handoff expression, shared engine <-> certifier.

One function owns the cheap-to-certified state translation so the
serving engine (serve/engine.py ``infer_cascade_handoff``) and the
certification harness (eval/certify.py ``certify_cascades``) compile the
SAME math — what is certified is exactly what serves.

The carried state (models/raft_stereo.forward_prologue) splits into:

* **tier-independent** leaves — the GRU hidden states (``nets``), the
  context features (``zqr``) and the low-res disparity (``disp``, always
  fp32): semantically mode-free, only their storage dtype follows the
  tier's compute dtype.  The handoff CASTS them to the certified
  exemplar's dtypes, so the certified step executable (traced at warmup
  from a certified prologue) sees exactly the signature it was traced
  with;
* **tier-specific** leaves — the correlation state (``corr``): an int8
  tier's corr state is quantized rows + scales, structurally different
  from the fp32 pyramid.  It cannot be cast; the cascade prologue stages
  the certified tier's corr state alongside the cheap one (built from
  the same images in the same dispatch) and the handoff SWAPS it in.

The staging cost — the documented builder decision (docs/serving.md
"Tier cascade"): the cascade prologue runs BOTH tiers' prologues, so a
cascade join pays one extra fp32 encode + correlation build and holds
the certified corr state in device memory for the cheap leg's duration.
Rebuilding at handoff instead would halve prologue cost but stall the
certified batch behind a fresh encode at every promotion — and an
early-promotion trigger would make that stall data-dependent.
"""

from __future__ import annotations

import jax

__all__ = ["handoff_state"]


def handoff_state(state, stage):
    """Assemble the certified-tier carried state at the tier handoff.

    ``state`` is the cheap tier's carried state after its drafting leg;
    ``stage`` is the certified tier's staged prologue state (same batch,
    same images).  Tier-independent leaves carry over from ``state``
    (cast leaf-by-leaf to ``stage``'s dtypes — the certified trace's
    exact signature); the tier-specific corr state comes from ``stage``.
    ``disp`` is fp32 on every tier (the model contract) and carries over
    uncast.
    """
    def carry(part):
        return jax.tree.map(lambda c, x: c.astype(x.dtype),
                            state[part], stage[part])

    return {"nets": carry("nets"), "zqr": carry("zqr"),
            "corr": stage["corr"], "disp": state["disp"]}
