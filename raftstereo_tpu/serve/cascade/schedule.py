"""The cascade schedule grammar: ``"<cheap>:<iters>+fp32:<iters>"``.

A schedule string is the cascade's identity everywhere — the request's
``accuracy=cascade:<schedule>`` value, the certification-manifest key
(eval/certify.py), the ``cascade_schedules_total`` metric label and the
``/healthz`` listing all use the CANONICAL form produced by
:meth:`CascadeSchedule.schedule`, so one cascade is one string.

Grammar (version :data:`SCHEDULE_VERSION`):

* legs are ``MODE:ITERS`` joined by ``+``, executed left to right;
* a leg's mode token is a precision mode (``int8``/``bf16``/``fp32``,
  ops/quant.MODES) or an accuracy-tier name (``turbo``/``fast``/
  ``certified``, normalized through ops/quant.TIER_MODES);
* version 1 allows exactly TWO legs — one cheap drafting leg and one
  certifying leg — because the engine stages exactly one certified
  correlation state alongside the cheap one (serve/engine.py
  ``infer_cascade_prologue``); the parser accepts the general grammar so
  a longer schedule fails validation with a version message, not a
  syntax error;
* the LAST leg must run the certified mode (``fp32``): a cascade's
  contract is that the answer leaves the certified executables;
* the first leg must NOT be ``fp32`` — that is not a cascade, it is the
  monolithic certified path.

Granularity: every leg's iteration count must be a positive multiple of
the scheduler's ``iters_per_step`` (the handoff happens at a step
boundary — ``validate_schedule``), and the total must fit ``max_iters``.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional, Tuple

__all__ = ["MODE_COST", "SCHEDULE_VERSION", "CascadeSchedule", "cheapest",
           "parse_schedule", "validate_schedule"]

SCHEDULE_VERSION = 1

# The certified (final-leg) precision mode — the fp32 reference every
# tier and cascade is certified against (ops/quant.TIER_MODES
# ["certified"]; asserted against the vocabulary in parse_schedule, but
# spelled here so importing the grammar never drags the numerics stack
# in — config validation and the loadgen trace grammar parse schedules
# in processes with no jax).
CERT_MODE = "fp32"

# The tier vocabulary, spelled locally for the same no-jax-import
# reason as CERT_MODE (tests/test_cascade.py asserts these match
# ops/quant.MODES / TIER_MODES, so drift fails tier-1).
_MODES = ("fp32", "bf16", "int8")
_TIER_MODES = {"certified": "fp32", "fast": "bf16", "turbo": "int8"}

# Relative per-iteration cost weights used ONLY to rank certified
# cascades when ``accuracy=certified`` resolves to the cheapest one
# (serve/server.py).  Coarse by design: int8 runs the MXU's native int8
# correlation pass, bf16 halves the multiply cost — the ranking is
# stable under any weights that keep fp32 > bf16 > int8 > 0.
MODE_COST = {"fp32": 1.0, "bf16": 0.5, "int8": 0.25}


@dataclasses.dataclass(frozen=True)
class CascadeSchedule:
    """A parsed, canonicalized cascade schedule: ``legs`` of
    ``(precision mode, iterations)`` executed left to right."""

    legs: Tuple[Tuple[str, int], ...]

    @property
    def cheap_mode(self) -> str:
        """Precision mode of the drafting (first) leg."""
        return self.legs[0][0]

    @property
    def cert_mode(self) -> str:
        """Precision mode of the certifying (last) leg — always fp32."""
        return self.legs[-1][0]

    @property
    def cheap_iters(self) -> int:
        """Iterations scheduled on the cheap leg before handoff."""
        return self.legs[0][1]

    @property
    def cert_iters(self) -> int:
        """Iterations scheduled on the certified leg (the K of K/total)."""
        return self.legs[-1][1]

    @property
    def total_iters(self) -> int:
        return sum(n for _, n in self.legs)

    @property
    def fp32_fraction(self) -> float:
        """SCHEDULED fp32-iteration fraction (the divergence trigger can
        raise the EXECUTED fraction — ``cascade_iterations_total``)."""
        return self.cert_iters / self.total_iters

    @property
    def schedule(self) -> str:
        """Canonical schedule string (the identity key everywhere)."""
        return "+".join(f"{m}:{n}" for m, n in self.legs)

    def cost(self) -> float:
        """Relative cost of one scheduled pass (see :data:`MODE_COST`)."""
        return sum(MODE_COST.get(m, 1.0) * n for m, n in self.legs)

    def __str__(self) -> str:
        return self.schedule


def parse_schedule(text: str) -> CascadeSchedule:
    """Parse + canonicalize a schedule string; raises ``ValueError`` with
    the exact defect (the HTTP 400 / config-assert payload)."""
    if not isinstance(text, str) or not text.strip():
        raise ValueError("cascade schedule must be a non-empty string "
                         "like 'int8:24+fp32:8'")
    legs = []
    for part in text.strip().split("+"):
        bits = part.split(":")
        if len(bits) != 2:
            raise ValueError(
                f"cascade leg {part!r} is not MODE:ITERS "
                f"(schedule {text!r})")
        mode, iters_txt = bits[0].strip(), bits[1].strip()
        # Tier names normalize to their precision mode so
        # "turbo:24+certified:8" and "int8:24+fp32:8" are ONE schedule.
        mode = _TIER_MODES.get(mode, mode)
        if mode not in _MODES:
            raise ValueError(
                f"cascade leg {part!r} names unknown mode/tier "
                f"{bits[0].strip()!r} (modes {list(_MODES)}, tiers "
                f"{sorted(_TIER_MODES)})")
        try:
            iters = int(iters_txt)
        except ValueError:
            raise ValueError(
                f"cascade leg {part!r} has non-integer iterations "
                f"(schedule {text!r})") from None
        if iters < 1:
            raise ValueError(
                f"cascade leg {part!r} must run >= 1 iteration")
        legs.append((mode, iters))
    if len(legs) != 2:
        raise ValueError(
            f"cascade schedule {text!r} has {len(legs)} leg(s); grammar "
            f"version {SCHEDULE_VERSION} takes exactly 2 "
            "(cheap drafting leg + certifying fp32 leg)")
    if legs[-1][0] != CERT_MODE:
        raise ValueError(
            f"cascade schedule {text!r} must END on the certified mode "
            f"{CERT_MODE!r} — the answer leaves the certified "
            "executables")
    if legs[0][0] == CERT_MODE:
        raise ValueError(
            f"cascade schedule {text!r} starts on {CERT_MODE!r}: that is "
            "the monolithic certified path, not a cascade")
    return CascadeSchedule(tuple(legs))


def validate_schedule(sched: CascadeSchedule, *,
                      iters_per_step: Optional[int] = None,
                      max_iters: Optional[int] = None) -> CascadeSchedule:
    """Check a parsed schedule against the scheduler's granularity: the
    handoff happens at a step boundary, so every leg must be a multiple
    of ``iters_per_step``, and the total must fit ``max_iters``.  Returns
    the schedule for chaining; raises ``ValueError``."""
    if iters_per_step is not None:
        for mode, iters in sched.legs:
            if iters % iters_per_step:
                raise ValueError(
                    f"cascade leg {mode}:{iters} of {sched} is not a "
                    f"multiple of iters_per_step {iters_per_step} — the "
                    "tier handoff happens at a step boundary")
    if max_iters is not None and sched.total_iters > max_iters:
        raise ValueError(
            f"cascade schedule {sched} totals {sched.total_iters} "
            f"iterations > max_iters {max_iters}")
    return sched


def cheapest(schedules: Iterable[CascadeSchedule]
             ) -> Optional[CascadeSchedule]:
    """The cascade ``accuracy=certified`` resolves to: lowest scheduled
    cost, canonical-string tie-break so resolution is deterministic
    across processes.  None when no cascade is certified."""
    pool = list(schedules)
    if not pool:
        return None
    return min(pool, key=lambda s: (s.cost(), s.schedule))
