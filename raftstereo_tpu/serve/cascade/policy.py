"""Pure cascade-promotion policy: when a slot leaves the cheap tier.

Same design contract as ``serve/sched/policy.py``: every function here
is pure (no clocks, no engine, no locks) so the promotion behaviour
unit-tests deterministically without a device, and the scheduler calls
them with explicit state.

The divergence trigger watches the SAME signal family as the adaptive
stream controller (``stream/controller.py``): an exponential moving
average of the per-step mean |Δdisparity| on the low-res grid.  A cheap
tier that is converging produces a shrinking delta; a spike means the
cheap tier's updates are thrashing on this pair (quantization noise
feeding back through the correlation lookup), so the remaining iteration
budget is better spent on the certified executables — the slot promotes
EARLY and every remaining iteration runs fp32.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["DIVERGENCE_DECAY", "promotion_kind", "should_promote",
           "update_ema"]

# EMA decay d: ema' = d * ema + (1 - d) * delta.  Same form as
# stream/controller.update_ema; slightly faster than the controller's
# default because a cascade's cheap leg is tens of iterations, not
# hundreds of frames — the trigger must react within the leg.
DIVERGENCE_DECAY = 0.6


def update_ema(ema: Optional[float], delta: float,
               decay: float = DIVERGENCE_DECAY) -> float:
    """One EMA update of the per-step disparity delta; ``None`` seeds
    the average with the first observation (no cold-start bias toward
    zero — a zero seed would mask an immediately-divergent pair for
    several boundaries)."""
    if ema is None:
        return float(delta)
    return decay * float(ema) + (1.0 - decay) * float(delta)


def should_promote(done_iters: int, cheap_iters: int,
                   ema: Optional[float],
                   threshold: Optional[float]) -> Tuple[bool, bool]:
    """Whether a cascade slot hands off to the certified tier at this
    boundary.  Returns ``(promote, early)``:

    * scheduled promotion — the cheap leg's iterations are done
      (``done_iters >= cheap_iters``; ``>`` only when the certified
      batch was full at the scheduled boundary and the slot kept cheap-
      stepping);
    * early promotion — the divergence trigger fired: an EMA exists
      (at least one boundary observed) and exceeds ``threshold``.
      ``threshold`` None or <= 0 disables the trigger entirely.
    """
    if done_iters >= cheap_iters:
        return True, False
    if threshold is not None and threshold > 0.0 and ema is not None \
            and ema > threshold:
        return True, True
    return False, False


def promotion_kind(early: bool) -> str:
    """The ``cascade_promotions_total{kind=}`` label for a promotion."""
    return "early" if early else "scheduled"
