"""Speculative tier cascade: cheap-tier iterations, certified answers.

RAFT-Stereo's anytime property (accuracy rises smoothly with GRU
iteration count) plus the per-(bucket, mode) running batches of the
iteration scheduler and the certified precision tiers sharing one weight
set enable a draft/verify-style serving policy: run most GRU iterations
on a cheap tier (int8/bf16) and hand the carried state to the certified
fp32 executables for the last K iterations.  Pure policy over existing
executables — no new kernels.

The subsystem splits the same way ``serve/sched`` does:

* :mod:`.schedule` — the versioned schedule grammar
  (``"int8:24+fp32:8"``) and its validation against the tier vocabulary
  and the scheduler's ``iters_per_step`` granularity;
* :mod:`.policy` — the pure divergence-trigger functions (an EMA of the
  per-step low-res disparity delta, the same signal family as
  ``stream/controller.py``) deciding when a cascade slot promotes to the
  certified tier;
* :mod:`.handoff` — the cross-tier state handoff expression shared by
  the serving engine and the certification harness, so what is certified
  is exactly what serves.
"""

import importlib

# Lazy (PEP 562) exports, same policy as the parent package: the
# schedule grammar and promotion policy are pure Python, but ``handoff``
# pulls jax — a scheduler or config import of the grammar must not drag
# the numerics stack in.
_EXPORTS = {
    "handoff_state": ".handoff",
    "DIVERGENCE_DECAY": ".policy",
    "promotion_kind": ".policy",
    "should_promote": ".policy",
    "update_ema": ".policy",
    "MODE_COST": ".schedule",
    "SCHEDULE_VERSION": ".schedule",
    "CascadeSchedule": ".schedule",
    "cheapest": ".schedule",
    "parse_schedule": ".schedule",
    "validate_schedule": ".schedule",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        rel = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    value = getattr(importlib.import_module(rel, __name__), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
