"""HTTP front-end for the serving subsystem (stdlib ``http.server`` only).

Endpoints:

* ``POST /predict`` — two request dialects, negotiated per request
  (docs/wire_format.md):

  - JSON (``Content-Type: application/json``): body ``{"left": <array>,
    "right": <array>, "iters": optional int}``; an ``<array>`` is either
    a nested JSON list or the compact form ``{"shape": [H, W, 3],
    "dtype": "float32", "data_b64": "..."}``.
  - binary (``Content-Type: application/x-raftstereo-frame``): one wire
    frame (raftstereo_tpu/wire), decoded chunk-at-a-time straight into
    plane staging — the request never exists as body + decoded copies
    at once.  Responses are binary iff the request's ``Accept`` names
    the wire type; error replies are always JSON.

  ``iters`` must
  be one of the server's configured levels (``iters`` /
  ``degraded_iters`` — those executables are warmed; arbitrary values
  would compile under load).  Replies 200 with ``{"disparity": <array>,
  "meta": {...}}``, 503 ``overloaded`` when admission control sheds, 504
  on a per-request timeout, 400 on a malformed body.  Every reply carries
  an ``X-Request-Id`` header (also ``meta.request_id``) — the trace id of
  the request's spans in ``/debug/trace``.  Under the iteration-level
  scheduler (``--sched``, docs/serving.md) the body also accepts
  ``deadline_ms`` (deadline-aware early exit: the reply carries the
  anytime result with ``meta.degraded`` true) and ``priority``
  (``high``/``normal``/``low``), and ``iters`` may be any multiple of
  ``iters_per_step`` up to ``max_iters``.  On a spatially-sharded
  server (``--spatial_shards``, docs/serving.md "Spatial sharding")
  the body also accepts ``"spatial": true/false`` — pairs above the
  single-chip ``max_image_dim`` ceiling auto-route spatial when the
  capability is advertised on ``/healthz``.
* ``GET /metrics`` — Prometheus text exposition (serve/metrics.py).
* ``GET /healthz`` — JSON liveness: queue depth, compiled buckets, config.
* ``GET /debug/trace?last=N`` — recent spans as downloadable Chrome
  trace-event JSON (open at ui.perfetto.dev); ``trace_id=`` filters to
  one request.
* ``POST /debug/profile`` — body ``{"seconds": S}``: on-demand
  ``jax.profiler`` window; 409 while a capture is already running.
* ``GET /debug/threads`` — all-thread stack dump (the batcher/HTTP
  deadlock surface earns this).
* ``GET /debug/vars`` — resolved ServeConfig + build info + engine state.
* ``GET /debug/sessions/<id>`` / ``POST /debug/sessions`` — export /
  import one streaming session's warm-start state (the wire half of
  session migration, docs/serving.md "Session migration"): the router
  moves state between backends through these on drain, restart, or
  backend loss.  The disparity rides as raw base64 bytes
  (``encode_array``), so a warm import is bitwise-identical to having
  stayed.

``ThreadingHTTPServer`` gives one thread per connection; they all funnel
into the single ``DynamicBatcher`` queue, which is where concurrency is
actually managed (admission control + micro-batching), so the HTTP layer
stays dumb on purpose.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import logging
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple, Union
from urllib.parse import unquote, urlparse

import numpy as np

from .. import wire
from ..config import ServeConfig
from ..obs import Tracer, build_info, dump_threads, trace_response
from ..utils.faults import FaultPlan
from ..utils.profiling import OnDemandProfiler, ProfilerBusy
from .batcher import DynamicBatcher, Overloaded, RequestTimedOut, ShuttingDown
from .engine import BatchEngine
from .httpbase import JsonRequestHandler
from .metrics import ServeMetrics
from .sched import IterationScheduler
from .spatial import (SPATIAL_ENDPOINT, admit_spatial, route_spatial,
                      capability as spatial_capability)

logger = logging.getLogger(__name__)

__all__ = ["StereoServer", "UnsupportedSnapshotCodec", "build_server",
           "decode_array", "encode_array", "snapshot_to_wire",
           "wire_to_snapshot"]


def encode_array(a: np.ndarray) -> Dict:
    """Compact JSON-safe array encoding (raw bytes, base64)."""
    a = np.ascontiguousarray(a)
    return {"shape": list(a.shape), "dtype": str(a.dtype),
            "data_b64": base64.b64encode(a.tobytes()).decode("ascii")}


def decode_array(obj: Union[Dict, list]) -> np.ndarray:
    """Inverse of ``encode_array``; nested JSON lists also accepted."""
    if isinstance(obj, list):
        return np.asarray(obj, np.float32)
    a = np.frombuffer(base64.b64decode(obj["data_b64"]),
                      dtype=np.dtype(obj["dtype"]))
    return a.reshape(obj["shape"]).astype(np.float32, copy=False)


class UnsupportedSnapshotCodec(ValueError):
    """A snapshot wire form carries a disparity codec this build cannot
    decode.  Mixed-fleet contract (docs/streaming.md "Durable
    sessions"): the importer answers the documented ``cold_schema``
    fallback — never garbage state, never a hard error."""


def _quantize_plane_int8(x: np.ndarray):
    """Host-side numpy mirror of ``ops/quant.quantize_rows`` (per-row
    symmetric int8 over the last axis, zero-amax rows pinned to scale
    1.0).  Returns ``(q, scale, max_abs_err)``; the dequant
    ``q.astype(f32) * scale`` is the EXACT array a decoder reproduces
    (same single multiply, so encoder-measured error is decoder truth
    — the per-snapshot exactness manifest rides on it)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    amax = np.max(np.abs(x), axis=-1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(x / scale[..., None]), -127, 127).astype(np.int8)
    deq = q.astype(np.float32) * scale[..., None]
    return q, scale, float(np.max(np.abs(deq - x)))


def snapshot_to_wire(snapshot: Dict, compress: str = "off",
                     compress_bound: float = 0.05) -> Dict:
    """JSON form of a ``SessionStore.export_state`` snapshot.

    ``compress="off"`` encodes the disparity as raw f32 base64 bytes so
    the round trip is bitwise (the warm-handoff parity assertion
    depends on it).  ``compress="int8"`` rides the ops/quant.py per-row
    symmetric int8 scheme (~4x fewer snapshot bytes) and carries a
    per-snapshot exactness manifest ``{max_abs_err, bound}``; a plane
    whose quantization error would exceed ``compress_bound`` (low-res
    px) falls back to the bitwise raw form — compression never costs
    more warmth than the manifest certifies.  The schema fingerprint
    grows a ``snapshot_codec`` field when int8 is actually used, so a
    peer that cannot decode it refuses cleanly (``cold_schema``).  The
    router and the session tier relay these bodies verbatim without
    decoding."""
    wire = dict(snapshot)
    plane = np.ascontiguousarray(snapshot["prev_disp_low"], np.float32)
    wire["prev_disp_low"] = encode_array(plane)
    if compress == "int8":
        q, scale, err = _quantize_plane_int8(plane)
        if err <= compress_bound:
            wire["prev_disp_low"] = {
                "codec": "int8",
                "shape": list(plane.shape),
                "q_b64": base64.b64encode(q.tobytes()).decode("ascii"),
                "scale_b64": base64.b64encode(
                    scale.tobytes()).decode("ascii"),
                "manifest": {"max_abs_err": err,
                             "bound": float(compress_bound)},
            }
            wire["schema"] = dict(snapshot.get("schema") or {},
                                  snapshot_codec="int8-v1")
    if snapshot.get("bucket_hw"):
        wire["bucket_hw"] = list(snapshot["bucket_hw"])
    return wire


def _decode_plane(prev) -> np.ndarray:
    """Decode a wire disparity plane: raw f32 (``decode_array`` form),
    nested lists, or the int8 codec.  Unknown codecs raise
    :class:`UnsupportedSnapshotCodec` (mixed fleets fall back
    ``cold_schema``, never garbage)."""
    if isinstance(prev, dict) and "codec" in prev:
        if prev["codec"] != "int8":
            raise UnsupportedSnapshotCodec(
                f"unknown snapshot codec {prev['codec']!r}")
        shape = tuple(int(s) for s in prev["shape"])
        q = np.frombuffer(base64.b64decode(prev["q_b64"]),
                          dtype=np.int8).reshape(shape)
        scale = np.frombuffer(base64.b64decode(prev["scale_b64"]),
                              dtype=np.float32).reshape(shape[:-1])
        return q.astype(np.float32) * scale[..., None]
    return decode_array(prev)


def wire_to_snapshot(obj: Dict) -> Dict:
    """Inverse of ``snapshot_to_wire`` (tolerates nested-list arrays —
    same contract as ``decode_array``; int8-codec planes are exactly
    dequantized here)."""
    snap = dict(obj)
    prev = obj.get("prev_disp_low")
    if isinstance(prev, (dict, list)):
        snap["prev_disp_low"] = _decode_plane(prev)
    if obj.get("bucket_hw"):
        snap["bucket_hw"] = tuple(int(x) for x in obj["bucket_hw"])
    return snap


def _response_prefs(obj) -> Dict:
    """Wire-encode kwargs for a negotiated binary response.

    The optional request field ``response`` selects the disparity
    encoding: ``{"encoding": "f32"|"int16", "compress": bool}``.
    Anything unrecognized raises — surfacing as the caller's clean 400,
    never a mid-encode 500 after inference already ran."""
    prefs = {"encoding": "f32", "compress": True}
    if obj is None:
        return prefs
    if not isinstance(obj, dict):
        raise ValueError("response preferences must be an object")
    unknown = set(obj) - {"encoding", "compress"}
    if unknown:
        raise ValueError(
            f"unknown response preference(s) {sorted(unknown)}")
    enc = obj.get("encoding", "f32")
    if enc not in ("f32", "int16"):
        raise ValueError(
            f"unknown response encoding {enc!r} (choose f32 or int16)")
    prefs["encoding"] = enc
    prefs["compress"] = bool(obj.get("compress", True))
    return prefs


def _resolve_cascade(srv: "StereoServer", text: str):
    """Resolve an explicit ``accuracy=cascade:<schedule>`` request to an
    advertised ``CascadeSchedule``.  Raises ``ValueError`` (the caller's
    clean 400) on a grammar defect or an unadvertised/uncertified
    schedule — the message names the certification manifest so the
    operator knows exactly which gate refused it."""
    from .cascade.schedule import parse_schedule

    try:
        canonical = parse_schedule(text).schedule
    except ValueError as e:
        raise ValueError(f"bad cascade schedule: {e}") from None
    sched = srv.cascades.get(canonical)
    if sched is None:
        reason = srv.cascade_reasons.get(
            canonical, "schedule not offered by this server (--cascades)")
        manifest = srv.config.cert_manifest or "none configured"
        raise ValueError(
            f"cascade {canonical!r} not advertised: {reason} "
            f"(certification manifest: {manifest})")
    return sched


def _outcome(code: int, obj: Dict) -> str:
    """Label value for ``serve_requests_total{outcome=}``."""
    if code == 200:
        return "ok"
    if code == 400:
        return "bad_request"
    if code == 404:
        return "not_found"
    if code == 411:
        return "length_required"
    if code == 413:
        return "too_large"
    if code == 503:
        return "shed" if obj.get("error") == "overloaded" else "unavailable"
    if code == 504:
        return "timeout"
    return "error"


class _Handler(JsonRequestHandler):
    server_version = "raftstereo-serve/1.0"
    _log = logger  # request chatter to this module's logger, not stderr

    # Response-format negotiation for the CURRENT /predict request:
    # None = JSON reply, else the wire-encode kwargs.  Handler instances
    # are reused across keep-alive requests, so do_POST resets this at
    # the top of every /predict before any dispatch can read it.
    _wire_ctx: Optional[Dict] = None

    # (trace_id, parent_span_id) continued from the CURRENT /predict
    # request's X-Trace-Context (httpbase.trace_of) — trace_id None
    # means the upstream said sampled=0 and every span this request
    # records silently no-ops (obs/trace.py).  Reset per request for
    # the same keep-alive reuse reason as _wire_ctx.
    _trace: Optional[Tuple[Optional[str], Optional[str]]] = None

    # ------------------------------------------------------------- plumbing
    # (_send/_json/_reject_body come from JsonRequestHandler, shared
    # byte-for-byte with the cluster router's handler.)
    def _finish(self, code: int, obj: Dict, endpoint: str, rid: str,
                t0: float,
                extra_headers: Optional[Dict[str, str]] = None) -> None:
        """Terminal JSON reply for a /predict request: attach the
        request id, count the labeled outcome, close the root trace
        span.  Error replies always land here — whatever was negotiated,
        an error body stays JSON (wire/negotiate.py)."""
        srv: "StereoServer" = self.server
        if code == 200 and "meta" in obj:
            obj["meta"]["request_id"] = rid
        headers = {"X-Request-Id": rid}
        headers.update(extra_headers or {})
        # Count + close the span BEFORE writing: a client that hangs up
        # mid-reply (BrokenPipeError out of _json) must still be counted,
        # and its trace must still have a root span.  The request span
        # therefore excludes the response write itself.
        outcome = _outcome(code, obj)
        srv.metrics.requests.labels(endpoint=endpoint, outcome=outcome).inc()
        tid, parent = self._trace if self._trace is not None else (rid, None)
        srv.tracer.record("request", t0, time.perf_counter(), tid,
                          parent_id=parent,
                          attrs={"endpoint": endpoint, "status": code,
                                 "outcome": outcome})
        body = json.dumps(obj).encode()
        if code == 200 and "disparity" in obj:
            srv.metrics.wire_bytes.labels(
                direction="out", format="json").inc(len(body))
        self._send(code, body, "application/json", headers)

    def _finish_ok(self, srv: "StereoServer", disparity: np.ndarray,
                   meta: Dict, endpoint: str, rid: str,
                   t0: float) -> None:
        """Terminal 200 for /predict: encode the disparity in whichever
        response format this request negotiated (``_wire_ctx``)."""
        ctx = self._wire_ctx
        if ctx is None:
            self._finish(200, {"disparity": encode_array(disparity),
                               "meta": meta}, endpoint, rid, t0)
            return
        meta = dict(meta)
        meta["request_id"] = rid
        frame = wire.encode_response(disparity, meta, **ctx)
        srv.metrics.wire_bytes.labels(
            direction="out", format="binary").inc(len(frame))
        srv.metrics.requests.labels(endpoint=endpoint, outcome="ok").inc()
        tid, parent = self._trace if self._trace is not None else (rid, None)
        srv.tracer.record("request", t0, time.perf_counter(), tid,
                          parent_id=parent,
                          attrs={"endpoint": endpoint, "status": 200,
                                 "outcome": "ok"})
        self._send(200, frame, wire.WIRE_CONTENT_TYPE,
                   {"X-Request-Id": rid})

    # ------------------------------------------------------------- endpoints
    def do_GET(self):
        srv: "StereoServer" = self.server
        # blackhole_backend chaos: hold EVERY reply (probes included —
        # they time out against probe_timeout_s, which is the point)
        # while a fault window is active; a no-op otherwise.
        self._maybe_blackhole()
        url = urlparse(self.path)
        if url.path == "/healthz":
            ready = srv.is_ready
            if srv.fault_plan.healthz_lie():
                # flap_probe chaos: this reply LIES ready=false on a
                # perfectly healthy server — probe flapping with no
                # underlying fault (the router must ride it out
                # without dropping accepted work).
                ready = False
            if srv.fault_plan.evict_due():
                # evict_sessions chaos: piggybacked on the probe
                # cadence — the store empties within one probe
                # interval of the armed offset, every live stream's
                # next frame re-anchors cold.
                srv.evict_sessions()
            health = {
                "status": "ok",
                # live vs ready (k8s-style): live = the process answers;
                # ready = warmup finished and not draining, i.e. traffic
                # routed here will not pay a cold compile.  The cluster
                # router gates on ready, never on live.
                "live": True,
                "ready": ready,
                "draining": srv.draining,
                "drained": srv.drained,
                "queue_depth": srv.queue_depth,
                "compiled_buckets": sorted(srv.engine.compiled_keys),
                "max_batch_size": srv.config.max_batch_size,
                "iters": srv.config.iters,
            }
            if srv.config.tiers:
                health["tiers"] = {
                    "advertised": {t: srv.tiers[t]
                                   for t in sorted(srv.tiers)},
                    "refused": dict(srv.tier_reasons),
                }
            if srv.cascades or srv.cascade_reasons:
                health["cascade"] = {
                    "advertised": sorted(srv.cascades),
                    "refused": dict(srv.cascade_reasons),
                    "divergence": srv.config.cascade_divergence,
                }
            if srv.cluster is not None:
                health["cluster"] = srv.cluster.stats()
            if srv.scheduler is not None:
                health["sched"] = srv.scheduler.stats()
            if srv.stream is not None:
                health["stream"] = {
                    "ladder": list(srv.config.stream.ladder),
                    "sessions_active": len(srv.stream.store),
                    "session_limit": srv.config.stream.session_limit,
                    "session_bytes": int(srv.stream.store.total_bytes()),
                    "session_budget_mb":
                        srv.config.stream.session_budget_mb,
                }
                if srv.tier_publisher is not None:
                    health["stream"]["tier"] = srv.tier_publisher.state()
            if getattr(srv.engine, "spatial_shards", 1) > 1:
                # Capability negotiation (serve/spatial/): a client
                # reads this block to learn whether — and at which
                # padded buckets — oversized pairs are served.
                health["spatial"] = spatial_capability(srv.config,
                                                      srv.engine)
            self._json(200, health)
        elif url.path == "/metrics":
            self._send(200, srv.metrics.render().encode(),
                       "text/plain; version=0.0.4")
        elif url.path == "/debug/trace":
            try:
                body, extra = trace_response(srv.tracer, url.query)
            except ValueError as e:  # e.g. ?last=abc
                self._json(400, {"error": f"bad query: {e}"})
                return
            self._send(200, body, "application/json", extra)
        elif url.path == "/debug/threads":
            self._send(200, dump_threads().encode(), "text/plain")
        elif url.path.startswith("/debug/sessions/"):
            # Session-state export (migration, docs/serving.md): the
            # snapshot serializes on the session lock, so an in-flight
            # frame completes first and the state is always consistent.
            sid = unquote(url.path[len("/debug/sessions/"):])
            snapshot = srv.export_session(sid)
            if snapshot is None:
                self._json(404, {"error": "no exportable state for "
                                          f"session {sid!r}"})
            else:
                scfg = srv.config.stream
                self._json(200, snapshot_to_wire(
                    snapshot, compress=scfg.snapshot_compress,
                    compress_bound=scfg.snapshot_compress_bound))
        elif url.path == "/debug/vars":
            lat = srv.metrics.latency
            self._json(200, {
                "config": dataclasses.asdict(srv.config),
                "build": build_info(),
                # Live request-latency percentiles (utils/profiling
                # quantile) — operators see p50/p99 without a
                # Prometheus stack.  null until the first request.
                "latency": ({
                    "count": lat.count,
                    "p50_ms": round(lat.quantile(0.5) * 1e3, 3),
                    "p99_ms": round(lat.quantile(0.99) * 1e3, 3),
                } if lat.count else None),
                "engine": {
                    "compiled_buckets": sorted(srv.engine.compiled_keys),
                    "queue_depth": srv.queue_depth,
                    "stream_sessions": (len(srv.stream.store)
                                        if srv.stream is not None else None),
                },
                "sched": (srv.scheduler.stats()
                          if srv.scheduler is not None else None),
                "cluster": (srv.cluster.stats()
                            if srv.cluster is not None else None),
                "tiers": {"advertised": dict(srv.tiers),
                          "refused": dict(srv.tier_reasons)},
                "ready": srv.is_ready,
                "draining": srv.draining,
                "trace": {"capacity": srv.tracer.capacity,
                          "recorded": srv.tracer.recorded,
                          "dropped": srv.tracer.dropped},
                "profile_running": srv.profiler.running,
            })
        else:
            self._json(404, {"error": f"no such path {self.path!r}"})

    def _debug_profile(self, srv: "StereoServer") -> None:
        """POST /debug/profile: bounded on-demand jax.profiler window,
        mutually exclusive with any running capture (HTTP 409)."""
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            length = -1
        if length < 0 or length > 1 << 16:  # tiny JSON only
            self.close_connection = True
            self._json(400, {"error": "bad Content-Length"})
            return
        raw = self.rfile.read(length) if length else b""
        try:
            payload = json.loads(raw) if raw else {}
            seconds = float(payload.get("seconds", 3.0))
        except Exception as e:
            self._json(400, {"error": f"bad request: {e}"})
            return
        try:
            info = srv.profiler.start(seconds)
        except ProfilerBusy as e:
            self._json(409, {"error": "profile already running",
                             "detail": str(e)})
            return
        except ValueError as e:
            self._json(400, {"error": str(e)})
            return
        self._json(200, info)

    def do_POST(self):
        srv: "StereoServer" = self.server
        # blackhole_backend chaos (see do_GET): requests are accepted
        # and parsed, replies held until the window closes — late, not
        # lost.  Arming POSTs land BEFORE their own window starts
        # (@t_ms offsets are measured from arming), so /debug/faults
        # itself is never blocked by the fault it arms.
        self._maybe_blackhole()
        path = urlparse(self.path).path
        if path == "/debug/profile":
            self._debug_profile(srv)
            return
        if path == "/debug/faults":
            # Runtime fault arming ({"faults": SPEC}) — the chaos
            # controller's seam (loadgen/chaos.py).
            raw = self._read_body(srv.config.max_body_mb)
            if raw is None:
                return
            try:
                spec = json.loads(raw or b"{}").get("faults", "")
                armed = srv.fault_plan.extend(str(spec or ""))
            except ValueError as e:
                self._json(400, {"error": f"bad fault spec: {e}"})
                return
            self._json(200, {"armed": [f.spec() for f in armed]})
            return
        if path == "/debug/drain":
            # Explicit drain (the router's scale-in/maintenance hook):
            # stop admitting /predict traffic, let everything already
            # queued or running finish, report drained on /healthz.
            # Drain any request body first (the router dialect sends
            # {"backend": ...}; unread bytes would desync keep-alive).
            if self._read_body(srv.config.max_body_mb) is None:
                return
            srv.start_drain()
            self._json(200, {"draining": True, "drained": srv.drained,
                             "queue_depth": srv.queue_depth,
                             "inflight": srv.inflight})
            return
        if path == "/debug/sessions":
            # Session-state import (migration): installs an exported
            # snapshot so the session's next in-order frame runs warm.
            # Cold fallbacks reply 200 with the outcome — losing warmth
            # is a performance event, never an error (PR 3 contract).
            raw = self._read_body(srv.config.max_body_mb)
            if raw is None:
                return
            if srv.stream is None:
                self._json(400, {"error": "streaming disabled on this "
                                          "server"})
                return
            try:
                obj = json.loads(raw)
                sid = str(obj.get("session_id", ""))
                snapshot = wire_to_snapshot(obj)
            except UnsupportedSnapshotCodec:
                # Mixed-fleet contract: a codec this build cannot
                # decode is the documented cold fallback, not an error
                # — the session re-anchors cold here, never garbage.
                self._json(200, {"session_id": sid,
                                 "outcome": "cold_schema"})
                return
            except Exception as e:
                self._json(400, {"error": f"bad snapshot: {e}"})
                return
            outcome = srv.import_session(snapshot)
            self._json(200, {"session_id": sid, "outcome": outcome})
            return
        # A router in front forwards its request id so the hop's spans
        # and the backend's spans share one trace (docs/observability.md).
        rid = (self.headers.get("X-Request-Id") or "")[:64] \
            or srv.tracer.new_trace_id()
        # Cross-hop trace continuation: a valid X-Trace-Context pins
        # this request's spans to the upstream trace (the parent is the
        # router's hop span); absent/malformed falls back to rid-as-
        # trace-id, sampled=0 suppresses every span.
        self._trace = self.trace_of(rid)
        t_req0 = time.perf_counter()
        endpoint = "predict"
        # Reset per request — the handler instance is reused across
        # keep-alive requests, so stale negotiation must never leak.
        self._wire_ctx = None
        # Refuse before buffering (shared body cap + chunked-encoding
        # policy; connection marked close): the reply rides through
        # _finish so the 411/413 is counted and traced like every other
        # /predict outcome.
        reject = self._reject_body(srv.config.max_body_mb)
        if reject is not None:
            code, payload = reject
            if code == 413:
                payload["limit_mb"] = srv.config.max_body_mb
            self._finish(code, payload, endpoint, rid, t_req0)
            return
        length = self._body_length
        binary_in = wire.is_wire_content_type(
            self.headers.get("Content-Type"))
        binary_out = wire.accepts_wire(self.headers.get("Accept"))
        srv.metrics.wire_bytes.labels(
            direction="in",
            format="binary" if binary_in else "json").inc(length)
        srv.metrics.wire_negotiations.labels(
            request="binary" if binary_in else "json",
            response="binary" if binary_out else "json").inc()
        # Bound CONCURRENT buffering, not just per-request size: each
        # in-flight JSON decode transiently holds body + base64 text +
        # decoded arrays (~3x the body); the binary path streams the
        # body chunk-at-a-time into plane staging (wire.FrameDecoder),
        # so it holds decoded arrays + one 64 KiB chunk — the slot then
        # bounds concurrent decoded pairs.  Without this, a handful of
        # parallel near-limit POSTs OOM the host before queue_limit
        # ever engages.
        wire_fields = None
        with srv.decode_slots:
            if binary_in:
                # Decoded planes may legitimately exceed the body byte
                # count (tile compression, uint8->float32 promotion is
                # 4x) — cap the allocation a header can demand at 8x
                # the body cap instead of the raw cap itself.
                dec = wire.FrameDecoder(
                    expect=wire.FRAME_REQUEST,
                    max_payload_bytes=int(
                        srv.config.max_body_mb * 2 ** 20) * 8)
                try:
                    complete = self._read_body_stream(length, dec.feed)
                except wire.WireError as e:
                    # Mid-body reject: the unread remainder can never
                    # be reframed — the connection must close.
                    self.close_connection = True
                    prefix = ("" if isinstance(e, wire.WireVersionError)
                              else "bad wire frame: ")
                    self._finish(400, {"error": f"{prefix}{e}"},
                                 endpoint, rid, t_req0)
                    return
                raw = b""
            else:
                # Drain the body BEFORE any reply: under HTTP/1.1
                # keep-alive, unread body bytes would be parsed as the
                # next request line.
                parts = []
                complete = self._read_body_stream(length, parts.append)
                raw = b"".join(parts)
                del parts
            if not complete:
                self._finish(400, {"error": "body shorter than "
                                            "Content-Length"},
                             endpoint, rid, t_req0)
                return
            if self.path != "/predict":
                self._finish(404, {"error": f"no such path {self.path!r}"},
                             "other", rid, t_req0)
                return
            # Readiness gate + in-flight count, atomically: a warming
            # server must not accept traffic (the request would stall
            # behind the warmup compiles), a draining one must not
            # admit new work — and an ADMITTED request is counted in
            # flight from the same lock acquisition, so drain's "finish
            # everything admitted" contract covers requests still
            # decoding or validating (``drained`` must never read true
            # while a request sits between the gate and dispatch).
            if not srv.try_begin_predict():
                detail = ("draining" if srv.draining
                          else "not ready (warming up)")
                self._finish(503, {"error": "unavailable",
                                   "detail": detail},
                             endpoint, rid, t_req0, {"Retry-After": "1"})
                return
            try:
                if binary_in:
                    req = dec.request()
                    # Mirror decode_array's contract: the engine always
                    # sees float32 (exact for uint8/int16 payloads).
                    left = np.ascontiguousarray(req.left, np.float32)
                    right = np.ascontiguousarray(req.right, np.float32)
                    payload = req.fields
                    del dec, req
                else:
                    payload = json.loads(raw)
                    left = decode_array(payload["left"])
                    right = decode_array(payload["right"])
                iters = payload.get("iters")
                session_id = payload.get("session_id")
                seq_no = payload.get("seq_no")
                deadline_ms = payload.get("deadline_ms")
                # Deadline propagation (docs/fault_tolerance.md): a
                # router hop forwards the client's remaining budget in
                # X-Deadline-Ms, already decremented by its own elapsed
                # time.  Merge via min() — the tighter of body field
                # and header wins — but only where a body deadline
                # would be accepted anyway (scheduler present, cold
                # request): elsewhere the header is silently ignored,
                # a propagated hint must never 400 a request that
                # did not ask for a deadline contract.
                hdr = self.headers.get("X-Deadline-Ms")
                if (hdr is not None and srv.scheduler is not None
                        and session_id is None):
                    try:
                        hdr_ms = float(hdr)
                    except ValueError:
                        hdr_ms = None
                    if hdr_ms is not None:
                        deadline_ms = (hdr_ms if deadline_ms is None
                                       else min(float(deadline_ms),
                                                hdr_ms))
                priority = payload.get("priority")
                accuracy = payload.get("accuracy")
                spatial = payload.get("spatial")
                if binary_out:
                    self._wire_ctx = _response_prefs(
                        payload.get("response"))
            except Exception as e:
                srv.end_predict()
                self._finish(400, {"error": f"bad request: {e}"},
                             endpoint, rid, t_req0)
                return
            del raw, payload
        try:
            self._predict_admitted(srv, endpoint, rid, t_req0, left, right,
                                   iters, session_id, seq_no, deadline_ms,
                                   priority, accuracy, spatial)
        finally:
            srv.end_predict()

    def _predict_admitted(self, srv: "StereoServer", endpoint, rid, t_req0,
                          left, right, iters, session_id, seq_no,
                          deadline_ms, priority, accuracy=None,
                          spatial=None) -> None:
        """Validation + dispatch of one admitted (gate-passed, decoded,
        in-flight-counted) /predict request."""
        # Downstream span recording (admission, batcher/scheduler
        # phases, stream warp) keys on the CONTINUED trace id — None
        # (sampled=0) makes every one a no-op without flag plumbing.
        tid = (self._trace or (rid, None))[0]
        mode = None
        cascade = None
        use_spatial = False
        try:
            # Channel count follows the model's input mode (sl/,
            # docs/structured_light.md): 3 for passive RGB, 12 for SL
            # pattern-conditioned stacks.  A mismatched request is a clean
            # 400 — there is no executable (nor cache key) for the other
            # modality on this engine.
            want_c = srv.engine.input_channels
            if left.ndim != 3 or left.shape[-1] != want_c \
                    or left.shape != right.shape:
                raise ValueError(
                    f"expected matching (H, W, {want_c}) pairs for "
                    f"input_mode={srv.engine.input_mode!r}, got "
                    f"{left.shape} / {right.shape}")
            # Spatial routing decides BEFORE the single-chip ceiling:
            # pairs above max_image_dim are exactly what the spatial
            # path exists for (serve/spatial/admission.py).  admit_
            # spatial rejects every v1 limitation (tiers, sessions,
            # scheduler fields, unwarmed buckets) as a clean 400, so
            # the remaining checks below are inert on this path.
            use_spatial = route_spatial(spatial, left.shape,
                                        srv.config, srv.engine)
            if use_spatial:
                endpoint = SPATIAL_ENDPOINT
                _, iters = admit_spatial(
                    srv.config, srv.engine, iters, accuracy, session_id,
                    deadline_ms, priority, left.shape)
            elif max(left.shape[:2]) > srv.config.max_image_dim:
                raise ValueError(
                    f"image side {max(left.shape[:2])} exceeds "
                    f"max_image_dim {srv.config.max_image_dim}")
            if accuracy is not None:
                # Accuracy tiers (ops/quant.py, docs/serving.md): only
                # ADVERTISED tiers resolve — a tier the certification
                # manifest refused (or a server without tiers) answers
                # with the recorded reason, never a silently-degraded
                # result or an unwarmed compile.  Cascades resolve
                # first: explicit "cascade:<schedule>" requests, and
                # "certified" rides the cheapest certified cascade when
                # one is offered (its answer still leaves the fp32
                # executables — that is the cascade contract).
                accuracy = str(accuracy)
                if accuracy.startswith("cascade:"):
                    cascade = _resolve_cascade(
                        srv, accuracy[len("cascade:"):])
                elif accuracy == "certified" and srv.cascades:
                    from .cascade.schedule import cheapest

                    cascade = cheapest(srv.cascades.values())
                if cascade is None:
                    if accuracy not in srv.tiers:
                        reason = srv.tier_reasons.get(
                            accuracy, "tier not offered by this server "
                                      "(--tiers)")
                        raise ValueError(
                            f"accuracy tier {accuracy!r} not advertised: "
                            f"{reason}")
                    mode = srv.tiers[accuracy]
                    if mode == srv.engine.default_mode:
                        # The tier IS the default path's program (e.g.
                        # "certified" on an fp32 server): normalize to
                        # None so the batcher/scheduler group it WITH
                        # default traffic — same executable, shared
                        # batches, one running state per bucket.
                        mode = None
                elif iters is not None:
                    raise ValueError(
                        f"iters is fixed by the cascade schedule "
                        f"{cascade} (omit it)")
                elif session_id is not None:
                    raise ValueError(
                        "session frames cannot run as cascades (v1): "
                        "the warm-start state is single-tier")
            if srv.scheduler is None and (deadline_ms is not None
                                          or priority is not None):
                raise ValueError(
                    "deadline_ms/priority require the iteration-level "
                    "scheduler (start the server with --sched)")
            if session_id is not None and (deadline_ms is not None
                                           or priority is not None):
                raise ValueError(
                    "session frames are scheduled as high-priority short "
                    "jobs; deadline_ms/priority cannot be set per frame")
            if session_id is not None:
                # Streaming frame: validated here, then dispatched outside
                # this block (the session path bypasses the micro-batcher).
                endpoint = "stream"
                if srv.stream is None:
                    raise ValueError(
                        "streaming disabled on this server (start with a "
                        "stream config / without --no_stream)")
                if iters is not None:
                    raise ValueError(
                        "iters cannot be combined with session_id: the "
                        "adaptive controller owns per-frame iterations "
                        "(configure --stream_ladder)")
                session_id = str(session_id)
                if seq_no is not None:
                    seq_no = int(seq_no)
                if not srv.config.cold_buckets:
                    hw = srv.engine.bucket_of(left.shape)
                    if srv.scheduler is not None:
                        # Scheduled frames ride the phase executables:
                        # every ladder level is served by the same step
                        # executable, so warmth is per bucket, not level.
                        if not srv.engine.is_sched_warm(
                                hw, srv.config.sched.iters_per_step,
                                mode=mode):
                            raise ValueError(
                                f"shape {tuple(left.shape[:2])} -> bucket "
                                f"{hw} not sched-warmed; configure "
                                f"--buckets")
                    else:
                        missing = [lv for lv in srv.config.stream.ladder
                                   if not srv.engine.is_stream_warm(
                                       hw, lv, mode=mode)]
                        if missing:
                            raise ValueError(
                                f"shape {tuple(left.shape[:2])} -> bucket "
                                f"{hw} stream levels {missing} not warmed; "
                                f"configure --buckets and --stream_warmup")
            if iters is not None and not use_spatial:
                iters = int(iters)
                if srv.scheduler is not None:
                    # Iteration-level scheduling serves ANY target from
                    # the same step executable — only the cap and the
                    # boundary granularity constrain it (no per-iters
                    # compile to protect against).
                    sc = srv.config.sched
                    if not 1 <= iters <= sc.max_iters \
                            or iters % sc.iters_per_step:
                        raise ValueError(
                            f"iters {iters} not served; must be a "
                            f"multiple of {sc.iters_per_step} in "
                            f"[1, {sc.max_iters}]")
                else:
                    # Only the configured (warmed) iteration levels:
                    # arbitrary client values would each compile a fresh
                    # executable under the engine lock — a trivially
                    # triggered latency DoS.
                    allowed = {srv.config.iters, srv.config.degraded_iters}
                    if iters not in allowed:
                        raise ValueError(
                            f"iters {iters} not served; choose from "
                            f"{sorted(allowed)}")
            if session_id is None and not use_spatial \
                    and not srv.config.cold_buckets:
                # Production setting (plain requests; session frames and
                # spatial requests have their own executable checks
                # above): shapes outside the warmed buckets are rejected
                # up front — an on-demand compile would stall every
                # queued request behind it.
                hw = srv.engine.bucket_of(left.shape)
                if srv.scheduler is not None:
                    if cascade is not None:
                        if not srv.engine.is_cascade_warm(
                                hw, srv.config.sched.iters_per_step,
                                cheap_mode=cascade.cheap_mode,
                                cert_mode=cascade.cert_mode):
                            raise ValueError(
                                f"shape {tuple(left.shape[:2])} -> "
                                f"bucket {hw} not cascade-warmed; "
                                f"configure it in --buckets")
                    elif not srv.engine.is_sched_warm(
                            hw, srv.config.sched.iters_per_step,
                            mode=mode):
                        raise ValueError(
                            f"shape {tuple(left.shape[:2])} -> bucket "
                            f"{hw} not sched-warmed; configure it in "
                            f"--buckets")
                else:
                    want = iters if iters is not None else srv.config.iters
                    if not srv.engine.is_warm(hw, want, mode=mode):
                        raise ValueError(
                            f"shape {tuple(left.shape[:2])} -> bucket {hw} "
                            f"(iters {want}) not warmed; configure it in "
                            f"--buckets")
        except Exception as e:
            self._finish(400, {"error": f"bad request: {e}"},
                         endpoint, rid, t_req0)
            return
        # Decode + validation done: the admission span closes where the
        # request either enters the batcher queue or the session path.
        srv.tracer.record("admission", t_req0, time.perf_counter(), tid,
                          attrs={"endpoint": endpoint,
                                 "shape": list(left.shape)})
        if use_spatial:
            self._spatial_dispatch(srv, endpoint, rid, t_req0,
                                   left, right, iters)
            return
        if session_id is not None:
            # Session frames bypass the micro-batcher: ordering within a
            # session is the point (frame N warm-starts from N-1), so they
            # serialize on the session lock and then the engine lock.
            # Admission control still applies — queue_limit bounds the
            # frames waiting on those locks, so a slow batch or compile
            # sheds stream traffic with 503s (holding decoded arrays in
            # unboundedly many blocked handler threads would grow host
            # RSS exactly like the unbounded queue the plain path rejects).
            with srv.stream_inflight_lock:
                if srv.stream_inflight >= srv.config.queue_limit:
                    srv.metrics.shed.inc()
                    self._finish(503, {"error": "overloaded",
                                       "detail": f"stream frames in flight "
                                                 f">= queue_limit "
                                                 f"{srv.config.queue_limit}"},
                                 endpoint, rid, t_req0,
                                 {"Retry-After": "1"})
                    return
                srv.stream_inflight += 1
            try:
                res = srv.stream.step(session_id, seq_no, left, right,
                                      trace_id=tid, mode=mode)
            except Overloaded as e:
                # Sched mode: the frame is a scheduler job and admission
                # can shed it there too — same backpressure contract as
                # the plain path (503 + Retry-After, never a 500).
                self._finish(503, {"error": "overloaded",
                                   "detail": str(e)},
                             endpoint, rid, t_req0, {"Retry-After": "1"})
                return
            except RequestTimedOut as e:
                self._finish(504, {"error": "timeout", "detail": str(e)},
                             endpoint, rid, t_req0)
                return
            except (TimeoutError, ShuttingDown) as e:
                self._finish(503, {"error": "unavailable",
                                   "detail": str(e)},
                             endpoint, rid, t_req0)
                return
            except Exception as e:
                self._finish(500, {"error": f"inference failed: {e}"},
                             endpoint, rid, t_req0)
                return
            finally:
                with srv.stream_inflight_lock:
                    srv.stream_inflight -= 1
            meta = {"session_id": res.session_id, "seq_no": res.seq_no,
                    "frame_idx": res.frame_idx, "iters": res.iters,
                    "warm": res.warm,
                    "update_ema": round(res.update_ema, 4),
                    "latency_ms": round(res.latency_s * 1e3, 3)}
            if accuracy is not None:
                meta["accuracy"] = accuracy
            if res.replica is not None:
                meta["replica"] = res.replica
            # Counted at the 200, not at admission: a request shed or
            # 400'd downstream was not SERVED at this tier, and the
            # metric is the per-tier adoption signal.
            srv.metrics.tier_requests.labels(
                tier=accuracy or "default").inc()
            self._finish_ok(srv, res.disparity, meta, endpoint, rid,
                            t_req0)
            return
        # Size the HTTP-side wait for what can actually be ahead of this
        # request: one in-flight batch (60 s) — or a cold XLA compile,
        # which takes minutes; with the 60 s slack a cold-bucket request
        # would get a spurious 503 while the server finishes the compile
        # and discards the result.
        hw = srv.engine.bucket_of(left.shape)
        if srv.scheduler is not None:
            ips = srv.config.sched.iters_per_step
            if cascade is not None:
                warm = srv.engine.is_cascade_warm(
                    hw, ips, cheap_mode=cascade.cheap_mode,
                    cert_mode=cascade.cert_mode)
            else:
                warm = srv.engine.is_sched_warm(hw, ips, mode=mode)
        else:
            levels = ([iters] if iters is not None
                      else [srv.config.iters, srv.config.degraded_iters])
            warm = all(srv.engine.is_warm(hw, lv, mode=mode)
                       for lv in levels)
        slack = 60.0 if warm else 600.0
        try:
            if srv.scheduler is not None:
                kwargs = dict(iters=iters, priority=priority,
                              deadline_ms=deadline_ms, trace_id=tid,
                              mode=mode)
                if cascade is not None:
                    # Keyword only when set: in cluster mode the
                    # dispatcher fills the scheduler slot and predates
                    # the cascade contract (cascades are refused there,
                    # so this branch never fires against it).
                    kwargs["cascade"] = cascade
                fut = srv.scheduler.submit(left, right, **kwargs)
            else:
                fut = srv.batcher.submit(left, right, iters,
                                         trace_id=tid, mode=mode)
        except ValueError as e:  # bad priority/deadline/target (sched)
            self._finish(400, {"error": f"bad request: {e}"},
                         endpoint, rid, t_req0)
            return
        except Overloaded as e:
            self._finish(503, {"error": "overloaded", "detail": str(e)},
                         endpoint, rid, t_req0, {"Retry-After": "1"})
            return
        except ShuttingDown:
            self._finish(503, {"error": "shutting down"},
                         endpoint, rid, t_req0)
            return
        try:
            # The batcher/scheduler enforces request_timeout_ms while
            # queued; the slack covers whatever can run ahead (batch
            # or cold compile).
            res = fut.result(
                timeout=srv.config.request_timeout_ms / 1000.0 + slack)
        except RequestTimedOut as e:
            self._finish(504, {"error": "timeout", "detail": str(e)},
                         endpoint, rid, t_req0)
            return
        except (TimeoutError, ShuttingDown) as e:
            self._finish(503, {"error": "unavailable",
                               "detail": str(e)},
                         endpoint, rid, t_req0)
            return
        except Exception as e:
            self._finish(500, {"error": f"inference failed: {e}"},
                         endpoint, rid, t_req0)
            return
        if srv.scheduler is not None:
            meta = {"iters": res.iters,
                    "target_iters": res.target_iters,
                    "degraded": res.degraded, "priority": res.priority,
                    "batch_slots": res.batch_slots,
                    "latency_ms": round(res.latency_s * 1e3, 3)}
            if getattr(res, "cascade", None) is not None:
                meta["cascade"] = res.cascade
                meta["promoted_early"] = res.promoted_early
        else:
            meta = {"iters": res.iters, "degraded": res.degraded,
                    "batch_size": res.batch_size,
                    "latency_ms": round(res.latency_s * 1e3, 3)}
        if accuracy is not None:
            meta["accuracy"] = accuracy
        if res.replica is not None:
            meta["replica"] = res.replica
        # Counted at the 200 (see the session path): only requests
        # actually served at the tier feed the adoption signal.
        srv.metrics.tier_requests.labels(tier=accuracy or "default").inc()
        self._finish_ok(srv, res.disparity, meta, endpoint, rid, t_req0)

    def _spatial_dispatch(self, srv: "StereoServer", endpoint, rid, t_req0,
                          left, right, iters) -> None:
        """Dispatch one admitted spatial request: straight to
        ``engine.infer_spatial``, bypassing the batcher AND the
        iteration scheduler (v1) — the pair owns the whole (1, N) mesh
        for its dispatch, so there is nothing to batch with and no
        iteration boundary to join at.  Admission control still
        applies: handler threads blocked on the engine lock are bounded
        by queue_limit, the same backpressure contract as the session
        path (decoded 4K pairs held in unboundedly many blocked threads
        would grow host RSS exactly like an unbounded queue)."""
        tid = (self._trace or (rid, None))[0]
        with srv.spatial_inflight_lock:
            if srv.spatial_inflight >= srv.config.queue_limit:
                srv.metrics.shed.inc()
                srv.metrics.spatial_requests.labels(outcome="shed").inc()
                self._finish(503, {"error": "overloaded",
                                   "detail": f"spatial requests in flight "
                                             f">= queue_limit "
                                             f"{srv.config.queue_limit}"},
                             endpoint, rid, t_req0, {"Retry-After": "1"})
                return
            srv.spatial_inflight += 1
        t0 = time.perf_counter()
        try:
            disp, _low, compiled = srv.engine.infer_spatial(
                left, right, iters)
        except Exception as e:
            srv.metrics.spatial_requests.labels(outcome="error").inc()
            self._finish(500, {"error": f"inference failed: {e}"},
                         endpoint, rid, t_req0)
            return
        finally:
            with srv.spatial_inflight_lock:
                srv.spatial_inflight -= 1
        t1 = time.perf_counter()
        srv.tracer.record("spatial_dispatch", t0, t1, tid,
                          attrs={"shards": srv.engine.spatial_shards,
                                 "iters": iters, "compile": compiled})
        srv.metrics.spatial_requests.labels(outcome="ok").inc()
        if not compiled:
            # Compile-free dispatches only, like the stream/sched
            # latency histograms — a cold_buckets compile would put a
            # minutes-long sample in a seconds-scale histogram.
            srv.metrics.spatial_latency.observe(t1 - t0)
        meta = {"iters": iters, "spatial": srv.engine.spatial_shards,
                "warm": not compiled,
                "latency_ms": round((t1 - t0) * 1e3, 3)}
        # Spatial serves only the base precision (admission rejects
        # tiers), so the adoption signal lands on the default tier.
        srv.metrics.tier_requests.labels(tier="default").inc()
        self._finish_ok(srv, disp, meta, endpoint, rid, t_req0)


class StereoServer(ThreadingHTTPServer):
    """HTTP server owning the engine + batcher + metrics + tracer.

    ``config.port == 0`` binds an ephemeral port; read the real one from
    ``server.server_address[1]`` (tests and ``bench.py --serve`` do).
    """

    daemon_threads = True

    def __init__(self, config: ServeConfig, engine: BatchEngine,
                 batcher: Optional[DynamicBatcher], metrics: ServeMetrics,
                 stream=None, tracer: Optional[Tracer] = None,
                 scheduler: Optional[IterationScheduler] = None,
                 cluster=None, start_ready: bool = True,
                 tiers: Optional[Dict[str, str]] = None,
                 tier_reasons: Optional[Dict[str, str]] = None,
                 cascades: Optional[Dict[str, object]] = None,
                 cascade_reasons: Optional[Dict[str, str]] = None,
                 fault_plan: Optional[FaultPlan] = None):
        assert (batcher is None) != (scheduler is None), (
            "exactly one of batcher (monolithic dispatch) or scheduler "
            "(iteration-level continuous batching) must be set")
        self.config = config
        # Advertised accuracy tiers (tier -> precision mode) and the
        # refusal reasons for requested-but-uncertified ones
        # (eval/certify.resolve_tiers; build_server fills both).  Direct
        # construction defaults to NO tiers — any `accuracy` field is a
        # clean 400, and no tier executables are ever compiled.
        self.tiers = dict(tiers or {})
        self.tier_reasons = dict(tier_reasons or {})
        # Advertised speculative tier cascades (canonical schedule string
        # -> CascadeSchedule) and refusal reasons, the cascade twin of
        # the tier tables above (eval/certify.resolve_cascades;
        # docs/serving.md "Tier cascade").
        self.cascades = dict(cascades or {})
        self.cascade_reasons = dict(cascade_reasons or {})
        self._engine = engine
        self.batcher = batcher
        self.scheduler = scheduler
        self.metrics = metrics
        self.stream = stream  # stream.runner.StreamRunner or None
        # serve/cluster/.ClusterDispatcher or None.  In cluster mode the
        # dispatcher ALSO fills the batcher/scheduler slot above (it
        # implements their submit contracts), so the request paths are
        # identical; this reference is for cluster-specific surfaces
        # (healthz block, drain fan-out).
        self.cluster = cluster
        self.tracer = tracer or Tracer(capacity=config.trace_buffer)
        # Serving-plane fault plan (utils/faults.py): armed from
        # RAFTSTEREO_FAULTS at construction, extended at runtime over
        # POST /debug/faults — always a plan (usually empty), so the
        # handler hooks never branch on None.  build_server shares ONE
        # plan between the server and its engine(s) so one /debug/faults
        # POST arms every hook in the process.
        self.fault_plan = (fault_plan if fault_plan is not None
                           else FaultPlan.from_env()).arm()
        # Write-behind publisher to the durable session tier
        # (stream/tier.TierPublisher); build_server wires it when
        # ``config.stream.tier`` is set.  None = local-pin-only.
        self.tier_publisher = None
        self.profiler = OnDemandProfiler(log_dir="runs/serve/profile")
        # Readiness (live vs ready on /healthz): set once warmup
        # finishes.  build_server passes start_ready=False and owns the
        # gate — it warms either before returning (blocking) or in a
        # background thread (warmup_async), during which the server is
        # live but refuses /predict with 503.  Direct construction
        # defaults to ready: whoever assembles the stack by hand has
        # already warmed (or chosen not to warm) the engine.
        self._ready = threading.Event()
        if start_ready:
            self._ready.set()
        self._flags_lock = threading.Lock()
        self._draining = False  # guarded_by: _flags_lock
        # /predict requests admitted and not yet answered (drain wants
        # "everything running finished", which queue depth alone misses).
        self._predict_inflight = 0  # guarded_by: _flags_lock
        # Admission control for the session path (which bypasses the
        # batcher queue): frames concurrently decoded-and-waiting on the
        # session/engine locks, shed with 503 beyond queue_limit.
        self.stream_inflight_lock = threading.Lock()
        self.stream_inflight = 0  # guarded_by: stream_inflight_lock
        # Same contract for the spatial path (which also bypasses the
        # batcher queue): requests concurrently holding decoded pairs
        # while waiting on the engine lock, shed beyond queue_limit.
        self.spatial_inflight_lock = threading.Lock()
        self.spatial_inflight = 0  # guarded_by: spatial_inflight_lock
        # Caps the number of request bodies being buffered/decoded at
        # once (each transiently costs ~3x its size); excess connections
        # queue on the semaphore instead of multiplying host RSS.
        self.decode_slots = threading.BoundedSemaphore(
            max(4, config.max_batch_size))
        super().__init__((config.host, config.port), _Handler)

    @property
    def port(self) -> int:
        return self.server_address[1]

    @property
    def queue_depth(self) -> int:
        """Requests waiting for dispatch, whichever front-end is active."""
        return (self.scheduler.queue_depth if self.scheduler is not None
                else self.batcher.queue_depth)

    @property
    def engine(self) -> BatchEngine:
        """Shape/warmth policy view for admission checks.  In cluster
        mode this resolves through the ReplicaSet ON EVERY ACCESS, not
        at construction: readiness is per-replica state, and replica 0
        may have failed warmup while others warmed — a snapshot taken
        before warmup would pin admission to its cold compile cache."""
        if self.cluster is not None:
            return self.cluster.rset.engine
        return self._engine

    # ------------------------------------------------- readiness + draining

    def mark_ready(self) -> None:
        """Warmup finished: the server may advertise ready and admit
        /predict traffic."""
        self._ready.set()

    @property
    def draining(self) -> bool:
        with self._flags_lock:
            return self._draining

    @property
    def is_ready(self) -> bool:
        """Routable: warmed AND not draining (what /healthz ``ready``
        reports and the cluster router gates on)."""
        return self._ready.is_set() and not self.draining

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        return self._ready.wait(timeout)

    def try_begin_predict(self) -> bool:
        """Atomic readiness gate + in-flight count: both under one lock
        so ``drained`` can never observe a request that passed the gate
        but is not yet counted (the drain-then-decommission flow polls
        ``drained`` and kills the process on true)."""
        with self._flags_lock:
            if not self._ready.is_set() or self._draining:
                return False
            self._predict_inflight += 1
            return True

    def end_predict(self) -> None:
        with self._flags_lock:
            self._predict_inflight -= 1

    @property
    def inflight(self) -> int:
        """Admitted /predict requests not yet answered.  Session frames
        are included: ``try_begin_predict`` wraps the WHOLE handler
        (cold and stream paths), so adding ``stream_inflight`` — the
        session path's separate admission-control counter — would
        double-count them."""
        with self._flags_lock:
            return self._predict_inflight

    def start_drain(self) -> None:
        """POST /debug/drain: stop admitting, finish everything already
        admitted (queued requests keep dispatching; running batches
        complete), then report ``drained`` on /healthz."""
        with self._flags_lock:
            self._draining = True
        if self.cluster is not None:
            self.cluster.drain()

    @property
    def drained(self) -> bool:
        """Drain complete: nothing queued, nothing running."""
        if not self.draining:
            return False
        if self.queue_depth or self.inflight:
            return False
        if self.scheduler is not None:
            active = getattr(self.scheduler, "active_slots", None)
            if callable(active) and active():
                return False
        return True

    # -------------------------------------------------- session migration

    def export_session(self, session_id: str) -> Optional[Dict]:
        """Host-side snapshot of one streaming session's warm-start
        state, or None when there is nothing warm to move.  In cluster
        mode ``self.stream`` IS the dispatcher, which resolves the
        owning replica; single-engine mode asks the StreamRunner
        directly.  Pure host numpy either way — zero device work, zero
        compiles (the retrace-guard contract for migration)."""
        if self.stream is None:
            return None
        return self.stream.export_session(session_id)

    def import_session(self, snapshot: Dict) -> str:
        """Install an exported snapshot; returns the handoff outcome
        (``warm`` / ``cold_schema`` / ``cold_lost`` — cold is a
        documented fallback, never an error)."""
        if self.stream is None:
            return "cold_lost"
        return self.stream.import_session(snapshot)

    def evict_sessions(self) -> int:
        """Drop every live streaming session (the ``evict_sessions``
        chaos hook, fired from /healthz so it lands within one probe
        interval of its armed offset).  ``self.stream`` is the
        StreamRunner or the cluster dispatcher — both implement
        ``evict_all``.  Returns sessions dropped; losing state is the
        documented cold fallback, never an error."""
        evictor = (getattr(self.stream, "evict_all", None)
                   if self.stream is not None else None)
        if evictor is None:
            return 0
        n = evictor()
        if n:
            logger.warning("fault injection: evicted %d live sessions", n)
        return n

    def close(self) -> None:
        """Stop accepting, drain the queue, release the socket."""
        self.shutdown()
        self.server_close()
        if self.tier_publisher is not None:
            self.tier_publisher.close()
        if self.batcher is not None:
            self.batcher.stop(drain=True)
        if self.scheduler is not None:
            self.scheduler.stop(drain=True)


def build_server(model, variables, config: ServeConfig,
                 metrics: Optional[ServeMetrics] = None,
                 tracer: Optional[Tracer] = None,
                 warmup_async: bool = False) -> StereoServer:
    """Wire engine(s) + dispatch + tracer + HTTP server; warm configured
    buckets.

    With ``config.cluster`` set, N engine replicas (one per device) are
    built behind a ClusterDispatcher instead of a single engine.

    ``warmup_async=False`` (default) warms before returning — the
    historical blocking behaviour, ready on return.  ``warmup_async=True``
    returns immediately with the server LIVE but NOT READY (/healthz
    ``ready: false``, /predict 503) and warms in a background thread —
    what a restarting production server wants: health-checkable at once,
    routable only when traffic will not pay a cold compile.

    The caller drives ``server.serve_forever()`` (blocking) or a thread,
    and ``server.close()`` on the way out.
    """
    metrics = metrics or ServeMetrics()
    tracer = tracer or Tracer(capacity=config.trace_buffer)
    # ONE fault plan for the whole process (server + every engine): a
    # single POST /debug/faults arms every hook, and a count budget is
    # consumed once process-wide (utils/faults.py).
    fault_plan = FaultPlan.from_env().arm()
    if config.spatial_shards > 1 and config.cluster is not None:
        raise ValueError(
            "spatial sharding and cluster replicas are mutually exclusive "
            "(v1): both partition the device set — run the spatial server "
            "as its own process behind the router instead")
    # Accuracy tiers: validated against the certification manifest BEFORE
    # anything is advertised or warmed (eval/certify.py) — an uncertified
    # tier is refused with a recorded reason, and its executables are
    # never compiled.
    tiers: Dict[str, str] = {}
    tier_reasons: Dict[str, str] = {}
    warm_modes = None
    if config.tiers:
        from ..eval.certify import resolve_tiers

        tiers, tier_reasons = resolve_tiers(
            config, model.config if model is not None else None)
        if tiers:
            from ..ops.quant import default_mode

            # model=None mirrors BatchEngine's own fallback (engine
            # stubs never dispatch; their keys just stay well-formed).
            base = ("fp32" if model is None
                    else default_mode(model.config))
            warm_modes = [base] + sorted(set(tiers.values()) - {base})
    # Speculative tier cascades: every schedule must certify — resolved
    # against the same manifest, refused with a recorded reason
    # (eval/certify.resolve_cascades, docs/serving.md "Tier cascade").
    cascades: Dict[str, object] = {}
    cascade_reasons: Dict[str, str] = {}
    if config.cascades:
        if config.cluster is not None:
            # v1 limitation: the cluster dispatcher's submit contract
            # predates cascades; a cascade request in cluster mode is a
            # clean 400 with this reason, never a crash mid-dispatch.
            cascade_reasons = {s: "cascades are single-engine in v1 "
                                  "(not offered in cluster mode)"
                               for s in config.cascades}
        else:
            from ..eval.certify import resolve_cascades

            cascades, cascade_reasons = resolve_cascades(
                config, model.config if model is not None else None)
    cluster = None
    stream = None
    if config.cluster is not None:
        from .cluster import ClusterDispatcher, ReplicaSet

        rset = ReplicaSet(model, variables, config, metrics, tracer=tracer,
                          fault_plan=fault_plan)
        cluster = ClusterDispatcher(rset, config, metrics, tracer=tracer)
        engine = rset.engine
        # The dispatcher fills whichever dispatch slot the mode uses —
        # the HTTP layer's request paths are unchanged; per-replica
        # batchers/schedulers live inside the replicas.
        scheduler = cluster if config.sched is not None else None
        batcher = cluster if config.sched is None else None
        if config.stream is not None:
            stream = cluster  # sticky session routing via the dispatcher

        def warm():
            rset.warmup(modes=warm_modes)
    else:
        engine = BatchEngine(model, variables, config, metrics,
                             fault_plan=fault_plan)
        scheduler = None
        if config.sched is not None:
            # Iteration-level continuous batching: the scheduler IS the
            # dispatch path — the micro-batcher is not started, admission
            # control lives in scheduler.submit, and session frames ride
            # the same scheduler as high-priority short jobs.  Warmth is
            # the four phase executables per bucket, not per iteration
            # level.
            scheduler = IterationScheduler(engine, config, metrics,
                                           tracer=tracer).start()
        if config.stream is not None:
            from ..stream.runner import StreamRunner  # local: avoids an
            # import cycle (stream.runner's engine builder imports this
            # pkg)
            stream = StreamRunner(engine, config.stream, metrics,
                                  tracer=tracer, scheduler=scheduler)
        batcher = None
        if scheduler is None:
            batcher = DynamicBatcher(engine, config, metrics,
                                     tracer=tracer).start()

        def warm():
            if config.sched is not None:
                if config.warmup:
                    engine.warmup_sched(
                        iters_per_step=config.sched.iters_per_step,
                        modes=warm_modes)
                    if cascades:
                        # Both legs' sched phases, the four cascade
                        # executables AND the handoff transition pair —
                        # a cascade request never compiles under traffic
                        # (the retrace-budget-0 e2e holds this).
                        engine.warmup_cascade(
                            iters_per_step=config.sched.iters_per_step,
                            schedules=list(cascades.values()))
            else:
                if config.warmup:
                    engine.warmup(modes=warm_modes)
                if config.stream is not None and config.stream_warmup:
                    engine.warmup_stream(ladder=config.stream.ladder,
                                         modes=warm_modes)
            if engine.spatial_shards > 1 and config.warmup:
                # Base precision only — admission refuses tiers on the
                # spatial path, so tier executables would be dead weight
                # (and the sharded compile is the longest in the system).
                engine.warmup_spatial()

    metrics.spatial_shards.set(
        engine.spatial_shards
        if getattr(engine, "spatial_shards", 1) > 1 else 0)
    server = StereoServer(config, engine, batcher, metrics, stream=stream,
                          tracer=tracer, scheduler=scheduler,
                          cluster=cluster, start_ready=False,
                          tiers=tiers, tier_reasons=tier_reasons,
                          cascades=cascades,
                          cascade_reasons=cascade_reasons,
                          fault_plan=fault_plan)
    if config.stream is not None and config.stream.tier is not None:
        from ..stream.tier import TierClient, TierPublisher

        scfg = config.stream
        runners = ([r.stream for r in cluster.rset.replicas
                    if r.stream is not None]
                   if cluster is not None else [stream])

        def _live_sids() -> List[str]:
            sids: List[str] = []
            for rnr in runners:
                sids.extend(rnr.store.session_ids())
            return sids

        publisher = TierPublisher(
            TierClient(scfg.tier[0], scfg.tier[1],
                       timeout_s=scfg.tier_timeout_s),
            export_fn=server.export_session,
            to_wire=lambda snap: snapshot_to_wire(
                snap, compress=scfg.snapshot_compress,
                compress_bound=scfg.snapshot_compress_bound),
            metrics=metrics,
            queue_limit=scfg.tier_queue_limit,
            retries=scfg.tier_retries,
            backoff_ms=scfg.tier_backoff_ms,
            reprobe_s=scfg.tier_reprobe_s,
            resync_fn=_live_sids,
        ).start()
        server.tier_publisher = publisher
        # Hand the publisher to every runner: StreamRunner.step enqueues
        # the SID after each completed frame (write-behind — the frame's
        # request path never touches the tier).
        for rnr in runners:
            rnr.publisher = publisher

    def warm_then_ready():
        try:
            warm()
        except Exception:
            # Live but never ready: probes keep failing readiness, the
            # router keeps traffic away, and the operator sees why here.
            logger.exception("warmup failed; server stays NOT READY")
            return
        server.mark_ready()

    if warmup_async:
        threading.Thread(target=warm_then_ready, daemon=True,
                         name="serve-warmup").start()
    else:
        # Blocking path: a warmup failure must raise (a silent
        # never-ready server would hang the caller's first request).
        warm()
        server.mark_ready()
    logger.info("serving on %s:%d (buckets=%s, max_batch=%d, iters=%d/%d, "
                "stream=%s, sched=%s, replicas=%s, ready=%s)",
                config.host, server.port,
                sorted(engine.compiled_keys) or "lazy",
                config.max_batch_size, config.iters, config.degraded_iters,
                list(config.stream.ladder) if config.stream else "off",
                "on" if scheduler is not None else "off",
                len(cluster.rset) if cluster is not None else "1 (single)",
                server.is_ready)
    return server
