"""Shared stdlib-only HTTP handler plumbing for the serving front-ends.

Both the single-server front-end (``serve/server.py``) and the cluster
router (``serve/cluster/router.py``) speak the same small dialect:
JSON replies with explicit Content-Length (keep-alive), and a bounded
Content-Length check before any body is buffered.  One base class keeps
the two handlers byte-identical on that dialect — a fix to the body-cap
or header logic lands in both.

The body cap is a POLICY ARGUMENT, not a constant: every call takes
``limit_mb`` from the caller's ``ServeConfig.max_body_mb``, which
auto-raises to fit the largest configured spatial bucket
(``config.spatial_body_mb`` — a 4K fp32 pair is ~95 MB of base64, far
over the default cap).  Over-limit requests get an explicit 413 naming
the limit, never a silent drop: a client sending a bucket-scale pair to
a server not configured for it must learn which knob to turn.

This module must stay importable without the engine/model stack: the
router is model-free (see serve/__init__.py's lazy exports).
"""

from __future__ import annotations

import json
import logging
from http.server import BaseHTTPRequestHandler
from typing import Dict, Optional

__all__ = ["JsonRequestHandler"]


class JsonRequestHandler(BaseHTTPRequestHandler):
    """JSON-over-HTTP/1.1 handler base: reply helpers + body cap.

    Subclasses set ``_log`` to their module logger (request chatter goes
    to ``logging``, never stderr) and their own ``server_version``."""

    protocol_version = "HTTP/1.1"  # keep-alive: load-gen reuses connections
    _log = logging.getLogger(__name__)

    def log_message(self, fmt, *args):
        self._log.debug("%s %s", self.address_string(), fmt % args)

    def _send(self, code: int, body: bytes, ctype: str,
              extra_headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj,
              extra_headers: Optional[Dict[str, str]] = None) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json",
                   extra_headers)

    def _content_length(self, limit_mb: float) -> Optional[int]:
        """Parse + bound Content-Length WITHOUT reading the body.

        Returns the length, or None when it is missing/unparseable/over
        ``limit_mb`` — the connection is then marked for close (refusing
        before buffering means the unread body can never be drained, so
        keep-alive would misparse it as the next request line).  The
        caller sends its own 413."""
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            length = -1
        if length < 0 or length > limit_mb * 2 ** 20:
            self.close_connection = True
            return None
        return length

    def _read_body(self, limit_mb: float) -> Optional[bytes]:
        """Bounded body read; replies 413 itself and returns None on a
        bad/oversize Content-Length."""
        length = self._content_length(limit_mb)
        if length is None:
            self._json(413, {"error": "body too large or bad "
                                      "Content-Length",
                             "limit_mb": limit_mb})
            return None
        return self.rfile.read(length) if length else b""
