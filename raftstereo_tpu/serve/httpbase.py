"""Shared stdlib-only HTTP handler plumbing for the serving front-ends.

Both the single-server front-end (``serve/server.py``) and the cluster
router (``serve/cluster/router.py``) speak the same small dialect:
JSON (or binary wire-frame, docs/wire_format.md) replies with explicit
Content-Length (keep-alive), and a bounded Content-Length check before
any body is buffered.  One base class keeps the two handlers
byte-identical on that dialect — a fix to the body-cap or header logic
lands in both.

The body cap is a POLICY ARGUMENT, not a constant: every call takes
``limit_mb`` from the caller's ``ServeConfig.max_body_mb``, which
auto-raises to fit the largest configured spatial bucket
(``config.spatial_body_mb`` — a 4K fp32 pair is 253.1 MiB of base64
JSON body, measured as ``len(json.dumps(payload))`` for a
3840x2160x3 pair, so the cap lands at ~316 MiB after the 25% decode
headroom; the binary wire format carries the same pair in under a
fifth of that).  Over-limit requests get an explicit 413 naming the
limit, never a silent drop: a client sending a bucket-scale pair to a
server not configured for it must learn which knob to turn.

Every refusal here carries an ``X-Request-Id`` header: pre-dispatch
errors (413/411/short reads) happen before the serving layer's tracer sees
the request, but the reply must still be joinable to client logs.

This module must stay importable without the engine/model stack: the
router is model-free (see serve/__init__.py's lazy exports).
"""

from __future__ import annotations

import json
import logging
import re
import uuid
from http.server import BaseHTTPRequestHandler
from typing import Callable, Dict, NamedTuple, Optional, Tuple

__all__ = ["JsonRequestHandler", "WIRE_CHUNK", "TRACE_HEADER",
           "TraceContext", "parse_trace_context", "format_trace_context"]

#: chunk size for the streaming body reader — also the upper bound on
#: what a streaming consumer (router forward, frame decoder) ever
#: buffers of the raw body at once.
WIRE_CHUNK = 64 * 1024

#: cross-hop trace context header (docs/observability.md).  Key-value
#: (not positional) because client-chosen ``X-Request-Id`` values — which
#: double as trace ids on the first hop — may themselves contain dashes
#: or dots, so no separator charset is safe for splitting.
TRACE_HEADER = "X-Trace-Context"

# trace id: whatever the X-Request-Id charset allows (it IS the trace id
# on un-headered requests); span id: the tracer's 16-hex form, but accept
# any short token — a foreign parent id is harmless, it just won't join.
_TRACE_TOKEN = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
_SPAN_TOKEN = re.compile(r"^[A-Za-z0-9._-]{1,32}$")


class TraceContext(NamedTuple):
    """Parsed ``X-Trace-Context``: the identity a request carries across
    hops.  ``sampled=False`` means "count me, don't span me" — every hop
    suppresses span recording but still serves the request normally."""

    trace_id: str
    parent_id: Optional[str]
    sampled: bool


def parse_trace_context(value: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``trace=<id>;parent=<spanid>;sampled=<0|1>`` header.

    Returns None for absent, malformed, or foreign-format values — the
    receiving hop then mints a fresh trace.  NEVER raises: a bad trace
    header must not be able to 500 a request (tests/test_obs.py)."""
    if not value or len(value) > 256:
        return None
    fields: Dict[str, str] = {}
    for part in value.split(";"):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        if not sep:
            return None
        fields[key.strip().lower()] = val.strip()
    trace_id = fields.get("trace", "")
    if not _TRACE_TOKEN.match(trace_id):
        return None
    parent = fields.get("parent") or None
    if parent is not None and not _SPAN_TOKEN.match(parent):
        return None
    sampled = fields.get("sampled", "1")
    if sampled not in ("0", "1"):
        return None
    return TraceContext(trace_id, parent, sampled == "1")


def format_trace_context(trace_id: str, parent_id: Optional[str] = None,
                         sampled: bool = True) -> str:
    """Render the ``X-Trace-Context`` value for an outbound hop."""
    out = f"trace={trace_id}"
    if parent_id:
        out += f";parent={parent_id}"
    return out + f";sampled={'1' if sampled else '0'}"


class JsonRequestHandler(BaseHTTPRequestHandler):
    """JSON-over-HTTP/1.1 handler base: reply helpers + body cap.

    Subclasses set ``_log`` to their module logger (request chatter goes
    to ``logging``, never stderr) and their own ``server_version``."""

    protocol_version = "HTTP/1.1"  # keep-alive: load-gen reuses connections
    _log = logging.getLogger(__name__)

    WIRE_CHUNK = WIRE_CHUNK  # class alias for subclass convenience

    def log_message(self, fmt, *args):
        self._log.debug("%s %s", self.address_string(), fmt % args)

    def request_id(self) -> str:
        """Propagated or fresh request id for THIS request.

        Computed per call, never cached on ``self``: handler instances
        are REUSED across keep-alive requests, so cached per-request
        state would leak one request's id into the next."""
        return (self.headers.get("X-Request-Id") or "")[:64] \
            or uuid.uuid4().hex

    def trace_context(self) -> Optional[TraceContext]:
        """Parsed inbound ``X-Trace-Context``, or None (fresh trace).

        Computed per call, never cached on ``self`` — same keep-alive
        reuse hazard as ``request_id``."""
        return parse_trace_context(self.headers.get(TRACE_HEADER))

    def trace_of(self, rid: str) -> Tuple[Optional[str], Optional[str]]:
        """(trace_id, parent_span_id) this request's spans should carry.

        A valid inbound context is CONTINUED (its trace id + parent span
        id); ``sampled=0`` yields trace_id None, which ``Tracer.record``
        treats as "don't record" — the one central guard that makes the
        sampled flag hold end-to-end without per-callsite plumbing.  No
        (or malformed) context: the request id doubles as the trace id,
        exactly the pre-stitching behaviour."""
        ctx = self.trace_context()
        if ctx is None:
            return rid, None
        if not ctx.sampled:
            return None, None
        return ctx.trace_id, ctx.parent_id

    def _maybe_blackhole(self) -> float:
        """``blackhole_backend@t_ms`` chaos seam (utils/faults.py):
        while the owning server's fault plan has an active blackhole
        window, HOLD this request — the connection was accepted, the
        request is parsed, but nothing is answered until the window
        closes (then the request proceeds normally).  Probes time out
        against their short ``probe_timeout_s`` and the router's
        circuit breaker opens; nothing is lost, only late.  Returns
        the seconds held (0.0 in the common no-fault path — the
        getattr keeps the seam free for servers without a plan)."""
        plan = getattr(self.server, "fault_plan", None)
        if plan is None:
            return 0.0
        return plan.blackhole_hold()

    def _send(self, code: int, body: bytes, ctype: str,
              extra_headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, obj,
              extra_headers: Optional[Dict[str, str]] = None) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json",
                   extra_headers)

    def _reject_body(self, limit_mb: float) -> Optional[Tuple[int, Dict]]:
        """Body-policy gate, applied BEFORE reading a single body byte.

        Returns ``(status, error_payload)`` when the request must be
        refused — the connection is then marked for close (an unread or
        unframed body can never be drained, so keep-alive would
        misparse it as the next request line) — or None to proceed, with
        the parsed length stashed in ``self._body_length``.

        Refusals:

        * ``Transfer-Encoding`` present -> 411: a chunked body has no
          Content-Length, would read as length 0 here, and its unread
          frames would desync the connection.
        * missing/unparseable/over-limit Content-Length -> 413 naming
          the limit.
        """
        te = (self.headers.get("Transfer-Encoding") or "").strip()
        if te:
            self.close_connection = True
            return 411, {"error": "Transfer-Encoding not supported; "
                                  "send a Content-Length body",
                         "transfer_encoding": te}
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            length = -1
        if length < 0 or length > limit_mb * 2 ** 20:
            self.close_connection = True
            return 413, {"error": "body too large or bad Content-Length",
                         "limit_mb": limit_mb}
        self._body_length = length
        return None

    def _content_length(self, limit_mb: float) -> Optional[int]:
        """Parse + bound Content-Length WITHOUT reading the body.

        Returns the length, or None when the body policy refuses it
        (see ``_reject_body``); the caller sends its own error reply.
        ``self.body_reject`` then holds the (status, payload) to send."""
        self.body_reject = self._reject_body(limit_mb)
        if self.body_reject is not None:
            return None
        return self._body_length

    def _read_body_stream(self, length: int,
                          sink: Callable[[bytes], None]) -> bool:
        """Drain exactly ``length`` body bytes in bounded chunks into
        ``sink(chunk)`` — the streaming read path: the full body never
        exists in this layer, only one <= WIRE_CHUNK slice at a time.

        Returns False on a short read (client hung up or lied about
        Content-Length); the connection is marked close — the stream
        position is undefined, nothing further can be parsed."""
        remaining = length
        while remaining:
            chunk = self.rfile.read(min(self.WIRE_CHUNK, remaining))
            if not chunk:
                self.close_connection = True
                return False
            remaining -= len(chunk)
            sink(chunk)
        return True

    def _read_body(self, limit_mb: float) -> Optional[bytes]:
        """Bounded whole-body read; replies itself (with an
        ``X-Request-Id``) and returns None on a policy refusal or a
        short read."""
        reject = self._reject_body(limit_mb)
        if reject is not None:
            code, payload = reject
            self._json(code, payload,
                       {"X-Request-Id": self.request_id()})
            return None
        parts = []
        if not self._read_body_stream(self._body_length, parts.append):
            self._json(400, {"error": "body shorter than Content-Length"},
                       {"X-Request-Id": self.request_id()})
            return None
        return b"".join(parts)
