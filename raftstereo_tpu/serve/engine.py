"""Shape-bucketed, padded-batch compiled inference engine.

The serving analogue of ``eval/runner.Evaluator``: one compiled executable
per (shape bucket, GRU iterations, GRU backend, input mode, precision
mode), reused across requests.
Three shape decisions keep the XLA compile count small and predictable:

* every image is padded with the SAME ``BucketPadder`` policy the Evaluator
  uses (divis_by alignment, then round-up to ``bucket_multiple``), so
  near-identical sizes share a bucket — and per-sample numerics match the
  single-image Evaluator bitwise;
* every dispatched batch is zero-padded along the batch axis to
  ``max_batch_size``, so a bucket compiles exactly once regardless of how
  many requests the micro-batcher coalesced (padding rows are dead weight
  on the MXU but convs/norms are per-sample, so real samples are
  unaffected);
* configured buckets are compiled eagerly at startup (``warmup``), so the
  first real request never pays the multi-second XLA compile.

The engine is deliberately synchronous and lock-serialized: ordering and
batching policy live in the batcher; this layer owns shapes, compiles and
device dispatch only.
"""

from __future__ import annotations

import contextlib
import logging
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ServeConfig
from ..ops.image import BucketPadder
from ..ops.pallas_gru import resolve_gru_backend
from ..ops.quant import MODES, config_for_mode, default_mode
from .metrics import ServeMetrics

logger = logging.getLogger(__name__)

__all__ = ["BatchEngine"]


class BatchEngine:
    """Batched test-mode forward behind a shape-bucketed compile cache."""

    def __init__(self, model, variables, config: ServeConfig,
                 metrics: Optional[ServeMetrics] = None, device=None,
                 fault_plan=None):
        self.model = model
        # Serving-plane chaos seam (utils/faults.py FaultPlan or None):
        # ``slow_replica@request=N:SECS`` injects dispatch latency at
        # the top of ``_dispatch`` — a replica that is alive but slow,
        # the hedged-request trigger.  Host-side only: the sleep
        # happens before any device work, so chaos runs add ZERO new
        # XLA compiles.
        self.fault_plan = fault_plan
        # ``device`` pins every executable (and the weights) to one chip:
        # the replicated cluster (serve/cluster/) builds one engine per
        # device from parallel.mesh.replica_devices, each with its OWN
        # jit wrappers — so each replica owns an independent compile
        # cache and the replicas never serialize on one another's
        # dispatch lock.  None keeps JAX's default placement (the
        # single-engine path, unchanged).
        self.device = device
        if device is not None:
            variables = jax.device_put(variables, device)
        self.variables = variables
        self.cfg = config
        self.metrics = metrics
        # Resolved test-mode GRU step backend ("fused" Pallas megakernel
        # or the "xla" reference step, ops/pallas_gru.py) — a MODE
        # component of every executable cache key: the two backends
        # compile different programs with different numerics, so a key
        # that omitted it could serve one backend's executable to the
        # other's request.  Resolved once per engine (platform + config
        # are fixed for the engine's lifetime); immutable thereafter.
        # (model=None: replica-lifecycle test stubs never dispatch — the
        # reference backend keeps their keys well-formed.)
        self.gru_backend = ("xla" if model is None
                            else resolve_gru_backend(model.config))
        # Precision modes (ops/quant.py): every executable key carries the
        # resolved mode ("fp32"/"bf16"/"int8") as its LAST component — the
        # per-request ``accuracy`` tier compiles a different program with
        # different numerics, so a key that omitted it could serve one
        # tier's executable to another tier's request.  ``default_mode``
        # is the base config's own numeric policy: requests without an
        # ``accuracy`` field resolve to it and run the base model
        # UNCHANGED (same executables, bitwise-identical results).
        self.default_mode = ("fp32" if model is None
                             else default_mode(model.config))
        # Input modality (sl/, docs/structured_light.md): joins every
        # executable cache key right before the precision mode.  A passive
        # and an SL model at the same bucket compile different programs
        # over different input ranks' worth of channels — a key that
        # omitted the modality could hand a 3-channel executable a
        # 12-channel batch.  Fixed per engine: the modality is a model-
        # architecture property (RAFTStereoConfig.input_mode), not a
        # per-request knob.
        self.input_mode = ("passive" if model is None
                           else model.config.input_mode)
        # Channels every raw input image carries (3 passive, 12 sl) —
        # the warmup zero-images and scheduler batch buffers are built at
        # this width.
        self.input_channels = (3 if model is None
                               else model.config.input_channels)
        # mode -> RAFTStereo sharing ``variables`` (tier configs only
        # change numeric-policy fields, so the fp32 weights apply to all;
        # flax casts per-module at apply time).  Built lazily: a server
        # with no tiers never constructs the extra models.
        self._models = {self.default_mode: model}  # guarded_by: _lock
        self._fns: Dict[object, object] = {}  # guarded_by: _lock
        # Spatial sharding (parallel/spatial.py): the resolved space-axis
        # shard count — ServeConfig overrides the model config's default;
        # <= 1 disables the spatial entry points.  Validated eagerly so a
        # misconfigured server fails at build time, not at the first 4K
        # request.  The (1, N) mesh itself is built lazily on first use
        # (guarded_by: _lock) — constructing it pulls device topology,
        # which replica-lifecycle test stubs (model=None) never have.
        self.spatial_shards = int(
            getattr(config, "spatial_shards", 0)
            or (1 if model is None
                else getattr(model.config, "spatial_shards", 1)))
        self._spatial_mesh = None  # guarded_by: _lock
        if self.spatial_shards > 1:
            from ..parallel.spatial import validate_spatial_config
            assert model is not None, "spatial sharding needs a model"
            assert device is None, (
                "spatial sharding splits one request across devices and "
                "cannot run on a device-pinned (cluster replica) engine")
            validate_spatial_config(model.config)
        # (keyed (iters, mode) | ("stream", iters, mode) | sched phases)
        self._lock = threading.RLock()
        # Fine-grained lock for _compiled only: stat readers (/healthz)
        # must not block behind _lock, which is held across a whole device
        # dispatch (seconds) or compile (minutes).
        self._stats_lock = threading.Lock()
        # Compiled keys: (h, w, iters, gru_backend, input_mode, mode) for
        # the plain forward and (h, w, iters, "stream", gru_backend,
        # input_mode, mode) for the warm-start (flow_init) forward.
        # Spatial keys are arity 8: (h, w, iters, "spatial", "sN",
        # gru_backend, input_mode, mode) — the shard count rides as the
        # STRING "sN" at position 4 so the mixed-arity key set stays
        # sortable (ints at 0-2, strings from 3 on; /healthz sorts the
        # whole set for a stable compiled_buckets listing).
        self._compiled: Set[Tuple] = set()  # guarded_by: _stats_lock
        self.last_batch_runtime: float = float("nan")  # guarded_by: _lock
        self.last_included_compile: bool = True  # guarded_by: _lock
        # Per-thread phase timing of the most recent dispatch THIS thread
        # ran (the batcher worker and concurrent stream handlers each read
        # their own): thread-local because an attribute would be overwritten
        # by whichever dispatch finished last.
        self._seg = threading.local()

    def _device_ctx(self):
        """Thread-local placement override for one dispatch: jit traces,
        input STAGING and transfers inside it target this engine's
        device (staging outside it would land on the global default
        device and pay a copy per dispatch).  A context manager (not a
        global config update) because concurrent replicas dispatch from
        different threads at once."""
        if self.device is None:
            return contextlib.nullcontext()
        return jax.default_device(self.device)

    # ----------------------------------------------------------- shape policy

    def _padder(self, shape: Sequence[int]) -> BucketPadder:
        return BucketPadder(shape, divis_by=self.cfg.divis_by,
                            bucket_multiple=self.cfg.bucket_multiple)

    def padder_of(self, shape: Sequence[int]) -> BucketPadder:
        """The padder an image of ``shape`` dispatches through — public for
        callers that unpad engine outputs themselves (the iteration-level
        scheduler unpads per leaving slot, serve/sched/scheduler.py)."""
        return self._padder(shape)

    def bucket_of(self, shape: Sequence[int]) -> Tuple[int, int]:
        """The padded (H, W) an image of ``shape`` executes at."""
        return self._padder(shape).bucket_hw

    @property
    def cache_stats(self) -> Dict[str, int]:
        with self._stats_lock:  # vs a concurrent add() resizing the set
            return {"compiled": len(self._compiled)}

    @property
    def compiled_keys(self) -> Set[Tuple]:
        with self._stats_lock:
            return set(self._compiled)

    def is_warm(self, hw: Tuple[int, int], iters: int,
                mode: Optional[str] = None) -> bool:
        """Whether (bucket, iters, mode) already has a compiled
        executable."""
        with self._stats_lock:
            return (hw[0], hw[1], iters, self.gru_backend, self.input_mode,
                    self._mode(mode)) in self._compiled

    def is_stream_warm(self, hw: Tuple[int, int], iters: int,
                       mode: Optional[str] = None) -> bool:
        """Whether (bucket, iters, mode) has a compiled WARM-START
        executable."""
        with self._stats_lock:
            return (hw[0], hw[1], iters, "stream", self.gru_backend,
                    self.input_mode, self._mode(mode)) in self._compiled

    # ------------------------------------------------------ spatial sharding

    def _spatial_shard_count(self, shards: Optional[int]) -> int:
        """Resolve an optional per-call shard count against the engine's
        fixed mesh.  The count is a CACHE-KEY component (a 2-shard and a
        4-shard program differ), but one engine owns one mesh — a
        mismatching request is a caller bug, not a new mesh."""
        n = self.spatial_shards if shards is None else int(shards)
        assert n == self.spatial_shards, (
            f"engine mesh has {self.spatial_shards} spatial shards, "
            f"request asked for {n}")
        assert n > 1, "spatial sharding is disabled (spatial_shards <= 1)"
        return n

    def _spatial_padder(self, shape: Sequence[int]) -> BucketPadder:
        """Spatial shape policy: same BucketPadder family as the plain
        path, with the alignment raised so the padded H splits into
        ``spatial_shards`` equal slabs of whole row-multiples
        (parallel/spatial.check_spatial_shape)."""
        from ..parallel.spatial import spatial_row_multiple
        rows = spatial_row_multiple(self.model.config) * self.spatial_shards
        divis = math.lcm(self.cfg.divis_by, rows)
        return BucketPadder(shape, divis_by=divis,
                            bucket_multiple=math.lcm(
                                self.cfg.bucket_multiple, divis))

    def spatial_bucket_of(self, shape: Sequence[int]) -> Tuple[int, int]:
        """The padded (H, W) an image executes at on the spatial path."""
        return self._spatial_padder(shape).bucket_hw

    def is_spatial_warm(self, hw: Tuple[int, int], iters: int,
                        mode: Optional[str] = None,
                        shards: Optional[int] = None) -> bool:
        """Whether (bucket, iters, mode) has a compiled SPATIAL
        executable at the engine's shard count."""
        n = self._spatial_shard_count(shards)
        with self._stats_lock:
            return (hw[0], hw[1], iters, "spatial", f"s{n}",
                    self.gru_backend, self.input_mode,
                    self._mode(mode)) in self._compiled

    def low_hw(self, hw: Tuple[int, int]) -> Tuple[int, int]:
        """The 1/factor grid a padded bucket's disparity field lives on —
        the shape of session state and of every ``flow_init``."""
        f = self.model.config.factor
        return hw[0] // f, hw[1] // f

    def session_schema(self) -> Dict[str, object]:
        """The engine-level state-schema fingerprint that gates warm
        session migration (``SessionStore.export_state``/``import_state``):
        two engines may exchange warm-start state only when the 1/f grid
        (``factor``) and the executables that will consume it
        (``input_mode``, ``gru_backend``) agree.  Pure metadata — no
        device work, no compiles."""
        cfg = getattr(self.model, "config", None)
        return {"factor": getattr(cfg, "factor", None),
                "input_mode": self.input_mode,
                "gru_backend": self.gru_backend}

    # -------------------------------------------------------- precision modes

    def _mode(self, mode: Optional[str]) -> str:
        """Resolve an optional precision mode to the concrete cache-key
        component (None = the base config's own mode — the default path,
        which may be the non-tier ``"base"`` token when the config's
        numeric mix matches no canonical tier config)."""
        if mode is None or mode == self.default_mode:
            return self.default_mode
        assert mode in MODES, f"unknown precision mode {mode!r}"
        return mode

    def _model_for(self, mode: str):  # guarded_by: _lock
        """The model a precision mode traces with.  Tier models are the
        base architecture with only the numeric-policy config fields
        swapped (ops/quant.config_for_mode) and share ``self.variables``
        — construction is pure Python module wiring, done once."""
        model = self._models.get(mode)
        if model is None:
            from ..models.raft_stereo import RAFTStereo
            model = self._models[mode] = RAFTStereo(
                config_for_mode(self.model.config, mode))
        return model

    # -------------------------------------------------------------- execution

    def _fn(self, iters: int, mode: str):  # guarded_by: _lock
        key = (iters, mode)
        if key not in self._fns:
            model = self._model_for(mode)
            self._fns[key] = jax.jit(
                lambda v, a, b, it=iters, m=model: m.forward(
                    v, a, b, iters=it, test_mode=True))
        return self._fns[key]

    def _stream_fn(self, iters: int, mode: str):  # guarded_by: _lock
        """Warm-start forward: takes a (B, H/f, W/f, 1) flow_init.  Cold
        frames pass zeros — bitwise-identical to the plain forward (tested
        in tests/test_model.py / tests/test_stream.py), so one executable
        per (bucket, level, mode) serves every frame of a stream."""
        key = ("stream", iters, mode)
        if key not in self._fns:
            self._fns[key] = self._model_for(mode).jitted_infer_init(iters)
        return self._fns[key]

    def _spatial_fn(self, iters: int, mode: str):  # guarded_by: _lock
        """Sharded warm-start forward over the (1, N) spatial mesh
        (parallel/spatial.jitted_spatial_infer_init).  ONE executable per
        (bucket, iters, mode, shards) serves cold requests AND session
        warm-start frames: zeros ``flow_init`` is bitwise-identical to
        the cold forward, the same property the stream path rests on."""
        key = ("spatial", iters, mode, self.spatial_shards)
        if key not in self._fns:
            from ..parallel.spatial import (jitted_spatial_infer_init,
                                            spatial_mesh)
            if self._spatial_mesh is None:
                self._spatial_mesh = spatial_mesh(self.spatial_shards)
            self._fns[key] = jitted_spatial_infer_init(
                self._model_for(mode), self._spatial_mesh, iters)
        return self._fns[key]

    def _sched_prologue_fn(self, mode: str):  # guarded_by: _lock
        """Compiled phase 1/3 of the split forward (encode + corr build):
        (variables, img1, img2, flow_init) -> carried state.  Cold slots
        pass zero flow_inits — bitwise-identical to flow_init=None, so one
        executable serves plain requests and warm stream frames."""
        key = ("sched", "prologue", mode)
        if key not in self._fns:
            model = self._model_for(mode)
            self._fns[key] = jax.jit(
                lambda v, a, b, f, m=model: m.forward_prologue(
                    v, a, b, flow_init=f))
        return self._fns[key]

    def _sched_step_fn(self, iters_per_step: int,
                       mode: str):  # guarded_by: _lock
        """Compiled single-boundary step: advances the whole running batch
        by ``iters_per_step`` GRU iterations."""
        key = ("sched", "step", iters_per_step, mode)
        if key not in self._fns:
            model = self._model_for(mode)
            self._fns[key] = jax.jit(
                lambda v, s, it=iters_per_step, m=model: m.forward_step(
                    v, s, iters=it))
        return self._fns[key]

    def _sched_epilogue_fn(self, mode: str):  # guarded_by: _lock
        """Compiled phase 3/3: final mask head + convex upsample."""
        key = ("sched", "epilogue", mode)
        if key not in self._fns:
            model = self._model_for(mode)
            self._fns[key] = jax.jit(
                lambda v, s, m=model: m.forward_epilogue(v, s))
        return self._fns[key]

    def _sched_join_fn(self):  # guarded_by: _lock
        """Compiled per-slot merge: leaves of ``incoming`` replace leaves
        of ``running`` where the (B,) mask is True.  Every state leaf is
        batch-leading (models/raft_stereo.forward_prologue), so a join
        touches exactly the joining slots' rows."""
        key = ("sched", "join")
        if key not in self._fns:
            def join(running, incoming, mask):
                def sel(x, y):
                    m = mask.reshape((-1,) + (1,) * (x.ndim - 1))
                    return jnp.where(m, y, x)
                return jax.tree.map(sel, running, incoming)
            self._fns[key] = jax.jit(join)
        return self._fns[key]

    def _cascade_prologue_fn(self, cheap_mode: str,
                             cert_mode: str):  # guarded_by: _lock
        """Compiled cascade phase 1: BOTH tiers' prologues over the same
        images in one dispatch — ``(cheap carried state, staged certified
        state)``.  Staging at the prologue (vs rebuilding at handoff) is
        the builder decision documented in serve/cascade/handoff.py: one
        extra fp32 encode + corr build per cascade join, certified corr
        held in device memory for the cheap leg, and in exchange the
        handoff itself is a cast+swap that never stalls the certified
        batch behind an encode."""
        key = ("cascade", "prologue", cheap_mode, cert_mode)
        if key not in self._fns:
            m_cheap = self._model_for(cheap_mode)
            m_cert = self._model_for(cert_mode)

            def fn(v, a, b, f, mc=m_cheap, mx=m_cert):
                return (mc.forward_prologue(v, a, b, flow_init=f),
                        mx.forward_prologue(v, a, b, flow_init=f))
            self._fns[key] = jax.jit(fn)
        return self._fns[key]

    def _cascade_handoff_fn(self, cheap_mode: str,
                            cert_mode: str):  # guarded_by: _lock
        """Compiled tier handoff: the shared cast+swap expression
        (serve/cascade/handoff.handoff_state — also what the certifier
        compiles) followed by a lane gather, so promoted slots land at
        their assigned slots in the certified batch in one dispatch."""
        key = ("cascade", "handoff", cheap_mode, cert_mode)
        if key not in self._fns:
            from .cascade.handoff import handoff_state

            def fn(s, stage, idx):
                out = handoff_state(s, stage)
                return jax.tree.map(lambda x: jnp.take(x, idx, axis=0),
                                    out)
            self._fns[key] = jax.jit(fn)
        return self._fns[key]

    def _cascade_delta_fn(self):  # guarded_by: _lock
        """Compiled divergence signal: per-slot mean |Δdisparity| on the
        low-res grid between consecutive boundaries — the EMA input of
        the cascade promotion trigger (serve/cascade/policy.py).  The
        body is mode-agnostic (disp is fp32 on every tier) but the cache
        key carries both cascade modes, like the join."""
        key = ("cascade", "delta")
        if key not in self._fns:
            self._fns[key] = jax.jit(
                lambda a, b: jnp.mean(jnp.abs(a - b), axis=(1, 2, 3)))
        return self._fns[key]

    def warmup(self, buckets=None, iters_list=None,
               modes: Optional[Sequence[str]] = None) -> List[Tuple]:
        """Compile the configured buckets before serving traffic.

        Covers both iteration levels (normal + degraded) so flipping into
        graceful degradation under load never stalls the queue behind a
        compile — exactly the moment a compile is least affordable — and
        every requested precision mode (``modes``; default = the base
        config's mode only) so a warmed accuracy tier never compiles
        under traffic either.  Returns the
        (h, w, iters, gru_backend, input_mode, mode) keys warmed.
        """
        buckets = list(buckets or self.cfg.buckets)
        # sorted, not set-ordered: the default {iters, degraded_iters} set
        # iterates in hash order, which made compile order and warmup logs
        # vary run to run.
        iters_list = sorted(iters_list
                            or {self.cfg.iters, self.cfg.degraded_iters})
        modes = list(modes or [self.default_mode])
        warmed = []
        for h, w in buckets:
            bh, bw = self.bucket_of((h, w, self.input_channels))
            for iters in iters_list:
                for mode in modes:
                    key = (bh, bw, iters, self.gru_backend,
                           self.input_mode, mode)
                    # is_warm, not a bare `in self._compiled`: membership
                    # is guarded by _stats_lock (RSA301).
                    if self.is_warm((bh, bw), iters, mode):
                        continue
                    zero = np.zeros((h, w, self.input_channels), np.float32)
                    t0 = time.perf_counter()
                    self.infer_batch([(zero, zero)], iters, mode=mode)
                    logger.info("warmup: bucket %dx%d iters=%d mode=%s "
                                "compiled in %.1fs", bh, bw, iters, mode,
                                time.perf_counter() - t0)
                    warmed.append(key)
        return warmed

    def warmup_stream(self, buckets=None, ladder: Sequence[int] = (),
                      modes: Optional[Sequence[str]] = None) -> List[Tuple]:
        """Compile the warm-start executables for every (bucket, ladder
        level, mode) before serving streams, so the adaptive controller
        can move between levels mid-stream without ever stalling a session
        behind an XLA compile.  Returns the (h, w, iters, "stream",
        gru_backend, input_mode, mode) keys warmed."""
        buckets = list(buckets or self.cfg.buckets)
        modes = list(modes or [self.default_mode])
        warmed = []
        for h, w in buckets:
            bh, bw = self.bucket_of((h, w, self.input_channels))
            # sorted for reproducible compile order/logs, same policy as
            # ``warmup`` (the ladder is descending by construction).
            for iters in sorted(ladder):
                for mode in modes:
                    key = (bh, bw, iters, "stream", self.gru_backend,
                           self.input_mode, mode)
                    if self.is_stream_warm((bh, bw), iters, mode):
                        continue
                    zero = np.zeros((h, w, self.input_channels), np.float32)
                    t0 = time.perf_counter()
                    self.infer_stream_batch([(zero, zero)], iters, [None],
                                            mode=mode)
                    logger.info("stream warmup: bucket %dx%d iters=%d "
                                "mode=%s compiled in %.1fs", bh, bw, iters,
                                mode, time.perf_counter() - t0)
                    warmed.append(key)
        return warmed

    @property
    def last_segments(self) -> Optional[Dict[str, object]]:
        """Phase timing of the last dispatch on THIS thread:
        ``{"pad", "dispatch", "host_fetch"}`` as (perf_counter t0, t1)
        windows plus ``"compile"`` — the raw material the batcher and
        stream runner turn into per-request trace spans (obs/trace.py)."""
        return getattr(self._seg, "last", None)

    def _pad_pairs(self, pairs):
        """Shared shape policy: per-pair BucketPadder padding plus batch-
        axis zero-padding to ``max_batch_size``, so the compile cache is
        keyed by bucket alone.  All pairs must map to one bucket (the
        batcher groups by bucket before dispatching)."""
        assert pairs, "empty batch"
        t_pad0 = time.perf_counter()
        assert len(pairs) <= self.cfg.max_batch_size, (
            f"batch {len(pairs)} exceeds max_batch_size "
            f"{self.cfg.max_batch_size}")
        padders = [self._padder(p[0].shape) for p in pairs]
        hw = padders[0].bucket_hw
        assert all(p.bucket_hw == hw for p in padders), (
            "mixed buckets in one batch: "
            f"{sorted({p.bucket_hw for p in padders})}")
        lefts, rights = [], []
        # Staging under _device_ctx too, not just the jit call: a pinned
        # replica's inputs must land on ITS device — staged on the global
        # default they would pay a device-to-device copy per dispatch and
        # serialize every replica's staging on one chip's stream.
        with self._device_ctx():
            for (im1, im2), padder in zip(pairs, padders):
                i1, i2 = padder.pad(jnp.asarray(im1, jnp.float32)[None],
                                    jnp.asarray(im2, jnp.float32)[None])
                lefts.append(i1)
                rights.append(i2)
            pad_rows = self.cfg.max_batch_size - len(pairs)
            i1 = jnp.concatenate(lefts, axis=0)
            i2 = jnp.concatenate(rights, axis=0)
            if pad_rows:
                i1 = jnp.pad(i1, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
                i2 = jnp.pad(i2, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
        self._seg.pad = (t_pad0, time.perf_counter())
        return padders, hw, i1, i2, pad_rows

    def _dispatch(self, key, call):
        """Lock-serialized device dispatch with compile-cache bookkeeping:
        runs ``call`` under the engine lock, fetches every output to host
        (fetch = completion), records timing/metrics.  Returns
        ``(host_outputs, included_compile)`` — the flag is per-call, not
        read back from shared engine state, so concurrent callers cannot
        race each other's compile accounting."""
        kind = ("stream" if "stream" in key
                else "spatial" if "spatial" in key else "batch")
        # tier = the key's precision-mode component (always last): a
        # compile under traffic must be attributable to the tier whose
        # warmup missed it.
        labels = dict(bucket=f"{key[0]}x{key[1]}", iters=str(key[2]),
                      mode=kind, tier=key[-1])
        if self.fault_plan is not None:
            # slow_replica chaos: sleep BEFORE taking the engine lock so
            # the injected latency models a slow device, not a convoy —
            # concurrent stream dispatches on other engines proceed.
            delay = self.fault_plan.dispatch_delay()
            if delay > 0.0:
                time.sleep(delay)
        with self._lock:
            with self._stats_lock:
                miss = key not in self._compiled
            if self.metrics is not None:
                (self.metrics.compile_misses if miss
                 else self.metrics.compile_hits).labels(**labels).inc()
            start = time.perf_counter()
            with self._device_ctx():
                out_dev = call()
            # Two measured phases: device compute (dispatch until the
            # result exists on device) and the device->host copy.  Both
            # still happen under the engine lock — fetch-before-release is
            # the engine's completion contract.
            jax.block_until_ready(out_dev)
            t_compute = time.perf_counter()
            out = [np.asarray(o, np.float32) for o in out_dev]
            t_fetch = time.perf_counter()
            runtime = t_fetch - start
            self.last_batch_runtime = runtime
            self.last_included_compile = miss
            with self._stats_lock:
                self._compiled.add(key)
        self._seg.last = {
            "pad": getattr(self._seg, "pad", None),
            "dispatch": (start, t_compute),
            "host_fetch": (t_compute, t_fetch),
            "compile": miss,
        }
        if self.metrics is not None and not miss:
            # The local, not self.last_batch_runtime: the lock is released
            # and a concurrent dispatch may have overwritten it (RSA301).
            self.metrics.batch_latency.observe(runtime)
        return out, miss

    def infer_batch(self, pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
                    iters: int, mode: Optional[str] = None
                    ) -> List[np.ndarray]:
        """Run a coalesced batch; returns one (H, W) disparity per pair.
        ``mode`` is the resolved precision mode (None = the default
        path); the micro-batcher groups by it, so a batch is always
        single-mode."""
        padders, hw, i1, i2, _ = self._pad_pairs(pairs)
        m = self._mode(mode)
        key = (hw[0], hw[1], iters, self.gru_backend, self.input_mode, m)
        (flow_up,), _ = self._dispatch(
            key, lambda: [self._fn(iters, m)(self.variables, i1, i2)[1]])
        return [padder.unpad(flow_up[i:i + 1])[0, ..., 0]
                for i, padder in enumerate(padders)]

    def infer_stream_batch(self, pairs: Sequence[Tuple[np.ndarray,
                                                       np.ndarray]],
                           iters: int,
                           flow_inits: Sequence[Optional[np.ndarray]],
                           mode: Optional[str] = None
                           ) -> List[Tuple[np.ndarray, np.ndarray, bool]]:
        """Warm-start batch: per pair an optional low-res ``flow_init``
        ((H/f, W/f) at the padded bucket shape; None = cold, zeros are
        substituted so the batch always runs the same executable).

        Returns one ``(disparity, disp_low, included_compile)`` per pair:
        the unpadded full-resolution (H, W) disparity, the PADDED 1/factor
        field — the session state a stream forward-warps into the next
        frame's ``flow_init`` (kept padded so it is already at the shape
        the next dispatch needs) — and whether this call paid the XLA
        compile.  Same bucket/batch-pad policy as ``infer_batch``.
        """
        assert len(pairs) == len(flow_inits), (len(pairs), len(flow_inits))
        padders, hw, i1, i2, pad_rows = self._pad_pairs(pairs)
        lh, lw = self.low_hw(hw)
        inits = []
        with self._device_ctx():  # stage on this replica's device
            for init in flow_inits:
                if init is None:
                    init = np.zeros((lh, lw), np.float32)
                init = np.asarray(init, np.float32)
                assert init.shape == (lh, lw), (
                    f"flow_init {init.shape} != low-res bucket shape "
                    f"{(lh, lw)} (bucket {hw}, factor "
                    f"{self.model.config.factor})")
                inits.append(jnp.asarray(init)[None, :, :, None])
            fi = jnp.concatenate(inits, axis=0)
            if pad_rows:
                fi = jnp.pad(fi, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
        m = self._mode(mode)
        key = (hw[0], hw[1], iters, "stream", self.gru_backend,
               self.input_mode, m)
        (low, up), miss = self._dispatch(
            key, lambda: self._stream_fn(iters, m)(self.variables, i1, i2,
                                                   fi))
        # .copy(): the low-res slice becomes long-lived session state; a
        # view would pin the whole (max_batch_size, ...) batch array in the
        # session store for its TTL.
        return [(padder.unpad(up[i:i + 1])[0, ..., 0],
                 low[i, :, :, 0].copy(), miss)
                for i, padder in enumerate(padders)]

    def infer_spatial(self, left: np.ndarray, right: np.ndarray,
                      iters: int, flow_init: Optional[np.ndarray] = None,
                      mode: Optional[str] = None,
                      shards: Optional[int] = None
                      ) -> Tuple[np.ndarray, np.ndarray, bool]:
        """ONE pair with image height sharded across the spatial mesh
        (parallel/spatial.py) — no batch axis: the request owns every
        chip of the (1, N) mesh for the duration of the dispatch.

        ``flow_init`` follows ``infer_stream_batch``: an optional
        (H/f, W/f) warm-start at the padded spatial bucket shape, None =
        cold (zeros — same executable).  Returns ``(disparity, disp_low,
        included_compile)``: the unpadded (H, W) disparity, the PADDED
        1/factor field (next-frame warm-start state), and whether this
        call paid the XLA compile.  The cache key carries the shard
        count: a 2-shard and a 4-shard program at the same bucket are
        different executables."""
        n = self._spatial_shard_count(shards)
        t_pad0 = time.perf_counter()
        padder = self._spatial_padder(left.shape)
        hw = padder.bucket_hw
        lh, lw = self.low_hw(hw)
        i1, i2 = padder.pad(jnp.asarray(left, jnp.float32)[None],
                            jnp.asarray(right, jnp.float32)[None])
        if flow_init is None:
            fi = jnp.zeros((1, lh, lw, 1), jnp.float32)
        else:
            flow_init = np.asarray(flow_init, np.float32)
            assert flow_init.shape == (lh, lw), (
                f"flow_init {flow_init.shape} != low-res spatial bucket "
                f"shape {(lh, lw)} (bucket {hw})")
            fi = jnp.asarray(flow_init)[None, :, :, None]
        self._seg.pad = (t_pad0, time.perf_counter())
        m = self._mode(mode)
        key = (hw[0], hw[1], iters, "spatial", f"s{n}", self.gru_backend,
               self.input_mode, m)
        (low, up), miss = self._dispatch(
            key, lambda: self._spatial_fn(iters, m)(self.variables, i1, i2,
                                                    fi))
        # .copy() for the same session-state-lifetime reason as
        # infer_stream_batch (here it only drops the channel axis' view).
        return (padder.unpad(up)[0, ..., 0], low[0, :, :, 0].copy(), miss)

    def warmup_spatial(self, buckets=None, iters_list=None,
                       modes: Optional[Sequence[str]] = None) -> List[Tuple]:
        """Compile the spatial executables for every configured spatial
        bucket before serving, so a 4K request never pays the (largest
        possible) XLA compile under traffic.  Returns the (h, w, iters,
        "spatial", "sN", gru_backend, input_mode, mode) keys warmed."""
        n = self._spatial_shard_count(None)
        buckets = list(buckets if buckets is not None
                       else getattr(self.cfg, "spatial_buckets", ()) or ())
        iters_list = sorted(iters_list or {self.cfg.iters})
        modes = list(modes or [self.default_mode])
        warmed = []
        for h, w in buckets:
            bh, bw = self.spatial_bucket_of((h, w, self.input_channels))
            for iters in iters_list:
                for mode in modes:
                    key = (bh, bw, iters, "spatial", f"s{n}",
                           self.gru_backend, self.input_mode, mode)
                    if self.is_spatial_warm((bh, bw), iters, mode):
                        continue
                    zero = np.zeros((h, w, self.input_channels), np.float32)
                    t0 = time.perf_counter()
                    self.infer_spatial(zero, zero, iters, mode=mode)
                    logger.info("spatial warmup: bucket %dx%d iters=%d "
                                "mode=%s shards=%d compiled in %.1fs", bh,
                                bw, iters, mode, n,
                                time.perf_counter() - t0)
                    warmed.append(key)
        return warmed

    # ------------------------------------------- iteration-level scheduling
    #
    # The phase executables behind serve/sched/ (docs/serving.md): the
    # split forward runs as prologue -> step x N -> epilogue, with the
    # carried state device-resident between boundaries.  All four phases
    # live in the same compile cache under arity-7 keys
    # (h, w, iters_per_step, phase, gru_backend, input_mode, mode) —
    # iters_per_step is 0 for the phases it cannot affect — so /healthz,
    # the RSA401 checker and the warmup accounting see them like every
    # other executable.

    def _sched_keys(self, hw: Tuple[int, int], iters_per_step: int,
                    mode: Optional[str] = None) -> List[Tuple]:
        g = self.gru_backend
        im = self.input_mode
        m = self._mode(mode)
        return [(hw[0], hw[1], 0, "sched_prologue", g, im, m),
                (hw[0], hw[1], iters_per_step, "sched_step", g, im, m),
                (hw[0], hw[1], 0, "sched_epilogue", g, im, m),
                (hw[0], hw[1], 0, "sched_join", g, im, m)]

    def is_sched_warm(self, hw: Tuple[int, int], iters_per_step: int,
                      mode: Optional[str] = None) -> bool:
        """Whether all four phase executables are compiled for (bucket,
        iters_per_step, mode)."""
        with self._stats_lock:
            return all(k in self._compiled
                       for k in self._sched_keys(hw, iters_per_step, mode))

    def _dispatch_state(self, key, call):
        """``_dispatch`` minus the host fetch: the scheduler's carried
        state stays on device between iteration boundaries, so completion
        here means block_until_ready, not a host copy.  Same lock
        serialization and compile-cache bookkeeping."""
        labels = dict(bucket=f"{key[0]}x{key[1]}", iters=str(key[2]),
                      mode=key[3], tier=key[-1])
        with self._lock:
            with self._stats_lock:
                miss = key not in self._compiled
            if self.metrics is not None:
                (self.metrics.compile_misses if miss
                 else self.metrics.compile_hits).labels(**labels).inc()
            start = time.perf_counter()
            with self._device_ctx():
                out = call()
            jax.block_until_ready(out)
            t_done = time.perf_counter()
            self.last_batch_runtime = t_done - start
            self.last_included_compile = miss
            with self._stats_lock:
                self._compiled.add(key)
        # Consume the pad window: only the prologue has one, and leaving
        # it set would stamp the stale window onto this thread's later
        # step/join/epilogue segments.
        pad = getattr(self._seg, "pad", None)
        self._seg.pad = None
        self._seg.last = {
            "pad": pad,
            "dispatch": (start, t_done),
            "host_fetch": (t_done, t_done),
            "compile": miss,
        }
        return out, miss

    def _sched_assemble(self, pairs, flow_inits, slots):
        """Shared join-group input assembly for the sched AND cascade
        prologues: each joining pair placed at its assigned batch slot
        (remaining slots are zero images — dead weight, exactly like
        batch padding rows).  Host-side assembly, ONE transfer at
        dispatch: out-of-jit ``.at[slot].set`` would copy the whole
        (B, H, W, 3) batch buffer once per joiner (same rationale as
        _pad_pairs).  Returns ``(hw, i1, i2, fi)`` and stamps the pad
        timing window."""
        assert len(pairs) == len(flow_inits) == len(slots), (
            len(pairs), len(flow_inits), len(slots))
        assert pairs, "empty join group"
        bsz = self.cfg.max_batch_size
        assert len(set(slots)) == len(slots) and all(
            0 <= s < bsz for s in slots), f"bad slots {slots}"
        t_pad0 = time.perf_counter()
        padders = [self._padder(p[0].shape) for p in pairs]
        hw = padders[0].bucket_hw
        assert all(p.bucket_hw == hw for p in padders), (
            "mixed buckets in one join group: "
            f"{sorted({p.bucket_hw for p in padders})}")
        lh, lw = self.low_hw(hw)
        i1 = np.zeros((bsz, hw[0], hw[1], self.input_channels), np.float32)
        i2 = np.zeros((bsz, hw[0], hw[1], self.input_channels), np.float32)
        fi = np.zeros((bsz, lh, lw, 1), np.float32)
        for (im1, im2), padder, init, slot in zip(pairs, padders,
                                                  flow_inits, slots):
            with self._device_ctx():  # tiny pad ops on our own device
                p1, p2 = padder.pad(jnp.asarray(im1, jnp.float32)[None],
                                    jnp.asarray(im2, jnp.float32)[None])
            i1[slot] = np.asarray(p1[0], np.float32)
            i2[slot] = np.asarray(p2[0], np.float32)
            if init is not None:
                init = np.asarray(init, np.float32)
                assert init.shape == (lh, lw), (
                    f"flow_init {init.shape} != low-res bucket shape "
                    f"{(lh, lw)} (bucket {hw})")
                fi[slot, :, :, 0] = init
        self._seg.pad = (t_pad0, time.perf_counter())
        return hw, i1, i2, fi

    def infer_sched_prologue(self, pairs: Sequence[Tuple[np.ndarray,
                                                         np.ndarray]],
                             flow_inits: Sequence[Optional[np.ndarray]],
                             slots: Sequence[int],
                             mode: Optional[str] = None):
        """Run the prologue for joining requests, each placed at its
        assigned batch slot.

        ``flow_inits`` follows ``infer_stream_batch``: an optional padded
        low-res warm-start per pair, None = cold (zeros).  Returns
        ``(hw, state, included_compile)`` with ``state`` device-resident.
        """
        hw, i1, i2, fi = self._sched_assemble(pairs, flow_inits, slots)
        m = self._mode(mode)
        key = (hw[0], hw[1], 0, "sched_prologue", self.gru_backend,
               self.input_mode, m)
        state, miss = self._dispatch_state(
            key, lambda: self._sched_prologue_fn(m)(self.variables, i1, i2,
                                                    fi))
        return hw, state, miss

    def infer_sched_step(self, hw: Tuple[int, int], state,
                         iters_per_step: int, mode: Optional[str] = None):
        """Advance the running batch by one boundary (``iters_per_step``
        GRU iterations); returns ``(state, included_compile)``."""
        m = self._mode(mode)
        key = (hw[0], hw[1], iters_per_step, "sched_step",
               self.gru_backend, self.input_mode, m)
        return self._dispatch_state(
            key, lambda: self._sched_step_fn(iters_per_step, m)(
                self.variables, state))

    def infer_sched_join(self, hw: Tuple[int, int], running, incoming,
                         mask: np.ndarray, mode: Optional[str] = None):
        """Merge ``incoming`` into ``running`` where ``mask`` (B,) is
        True; returns ``(state, included_compile)``.  The join body is
        mode-agnostic (a dtype-polymorphic tree select) but the key
        carries the mode: each tier's state pytree compiles its own
        program, and the warmup accounting must see that."""
        with self._device_ctx():  # the mask joins device-resident state
            mk = jnp.asarray(mask, bool)
        assert mk.shape == (self.cfg.max_batch_size,), mk.shape
        m = self._mode(mode)
        key = (hw[0], hw[1], 0, "sched_join", self.gru_backend,
               self.input_mode, m)
        return self._dispatch_state(
            key, lambda: self._sched_join_fn()(running, incoming, mk))

    def infer_sched_epilogue(self, hw: Tuple[int, int], state,
                             mode: Optional[str] = None):
        """Final mask + upsample for the whole batch, fetched to host:
        ``(disp_low (B, H/f, W/f, 1), disp_up (B, H, W, 1),
        included_compile)`` — the scheduler unpads per leaving slot
        (``padder_of``)."""
        m = self._mode(mode)
        key = (hw[0], hw[1], 0, "sched_epilogue", self.gru_backend,
               self.input_mode, m)
        (low, up), miss = self._dispatch_state(
            key, lambda: self._sched_epilogue_fn(m)(self.variables, state))
        return (np.asarray(low, np.float32), np.asarray(up, np.float32),
                miss)

    def warmup_sched(self, buckets=None, iters_per_step: int = 1,
                     modes: Optional[Sequence[str]] = None) -> List[Tuple]:
        """Compile all four phase executables for every configured bucket
        (and every requested precision mode) before scheduled traffic, so
        joins/steps/leaves never stall a running batch behind an XLA
        compile.  Sorted like ``warmup`` for reproducible compile order.
        Returns the keys warmed."""
        buckets = list(buckets or self.cfg.buckets)
        modes = list(modes or [self.default_mode])
        bsz = self.cfg.max_batch_size
        warmed = []
        for h, w in buckets:
            bh, bw = self.bucket_of((h, w, self.input_channels))
            for mode in modes:
                if self.is_sched_warm((bh, bw), iters_per_step, mode):
                    continue
                zero = np.zeros((h, w, self.input_channels), np.float32)
                t0 = time.perf_counter()
                hw, state, _ = self.infer_sched_prologue(
                    [(zero, zero)], [None], [0], mode=mode)
                state, _ = self.infer_sched_step(hw, state, iters_per_step,
                                                 mode=mode)
                state, _ = self.infer_sched_join(hw, state, state,
                                                 np.zeros(bsz, bool),
                                                 mode=mode)
                self.infer_sched_epilogue(hw, state, mode=mode)
                logger.info("sched warmup: bucket %dx%d iters_per_step=%d "
                            "mode=%s compiled in %.1fs", bh, bw,
                            iters_per_step, mode,
                            time.perf_counter() - t0)
                warmed.extend(self._sched_keys((bh, bw), iters_per_step,
                                               mode))
        return warmed

    # ------------------------------------------------- speculative cascades
    #
    # The cross-tier handoff executables behind serve/cascade/
    # (docs/serving.md "Tier cascade"): a cascade slot drafts on a cheap
    # tier's step executable and hands its carried state to the certified
    # tier's for the last K iterations.  Four cascade-specific phases —
    # dual prologue (cheap state + staged certified state), stage join,
    # handoff (cast + corr swap + lane gather) and the divergence delta —
    # under arity-8 keys (h, w, 0, phase, gru_backend, input_mode,
    # cheap_mode, cert_mode): every cascade executable is keyed by BOTH
    # precision modes (ints at 0-2, strings from 3 on, so the mixed-arity
    # key set stays sortable for /healthz).  The cheap/certified step and
    # epilogue executables are the UNMODIFIED per-mode sched phases — a
    # cascade adds no new math to either tier's iteration loop, which is
    # what keeps the single-tier paths bitwise-unchanged.

    def _cascade_pair(self, cheap_mode: Optional[str],
                      cert_mode: Optional[str]) -> Tuple[str, str]:
        cm, xm = self._mode(cheap_mode), self._mode(cert_mode)
        assert cm != xm, (
            f"cascade needs two distinct precision modes, got {cm!r} "
            "for both legs")
        return cm, xm

    def _cascade_keys(self, hw: Tuple[int, int],
                      cheap_mode: Optional[str] = None,
                      cert_mode: Optional[str] = None) -> List[Tuple]:
        g = self.gru_backend
        im = self.input_mode
        cm, xm = self._cascade_pair(cheap_mode, cert_mode)
        return [(hw[0], hw[1], 0, "cascade_prologue", g, im, cm, xm),
                (hw[0], hw[1], 0, "cascade_stage_join", g, im, cm, xm),
                (hw[0], hw[1], 0, "cascade_handoff", g, im, cm, xm),
                (hw[0], hw[1], 0, "cascade_delta", g, im, cm, xm)]

    def is_cascade_warm(self, hw: Tuple[int, int], iters_per_step: int,
                        cheap_mode: Optional[str] = None,
                        cert_mode: Optional[str] = None) -> bool:
        """Whether a (bucket, cheap_mode -> cert_mode) cascade is fully
        compiled: the four cascade phases AND both tiers' sched phase
        executables (the cascade rides them for its steps/epilogue)."""
        keys = self._cascade_keys(hw, cheap_mode, cert_mode)
        with self._stats_lock:
            warm = all(k in self._compiled for k in keys)
        return (warm
                and self.is_sched_warm(hw, iters_per_step, cheap_mode)
                and self.is_sched_warm(hw, iters_per_step, cert_mode))

    def infer_cascade_prologue(self, pairs: Sequence[Tuple[np.ndarray,
                                                           np.ndarray]],
                               flow_inits: Sequence[Optional[np.ndarray]],
                               slots: Sequence[int],
                               cheap_mode: Optional[str] = None,
                               cert_mode: Optional[str] = None):
        """Run BOTH tiers' prologues for joining cascade requests in one
        dispatch; returns ``(hw, state, stage, included_compile)`` —
        ``state`` is the cheap tier's carried state (EXACTLY what
        ``infer_sched_prologue(mode=cheap_mode)`` returns, so the slot
        joins the cheap tier's running batch indistinguishably) and
        ``stage`` is the certified tier's staged state, device-resident
        until the handoff swaps its corr in."""
        hw, i1, i2, fi = self._sched_assemble(pairs, flow_inits, slots)
        cm, xm = self._cascade_pair(cheap_mode, cert_mode)
        key = (hw[0], hw[1], 0, "cascade_prologue", self.gru_backend,
               self.input_mode, cm, xm)
        (state, stage), miss = self._dispatch_state(
            key, lambda: self._cascade_prologue_fn(cm, xm)(
                self.variables, i1, i2, fi))
        return hw, state, stage, miss

    def infer_cascade_stage_join(self, hw: Tuple[int, int], running,
                                 incoming, mask: np.ndarray,
                                 cheap_mode: Optional[str] = None,
                                 cert_mode: Optional[str] = None):
        """Merge newly staged certified state into the running batch's
        stage where ``mask`` (B,) is True — the side-car twin of
        ``infer_sched_join`` (same tree-select body, cascade-keyed);
        returns ``(stage, included_compile)``."""
        with self._device_ctx():
            mk = jnp.asarray(mask, bool)
        assert mk.shape == (self.cfg.max_batch_size,), mk.shape
        cm, xm = self._cascade_pair(cheap_mode, cert_mode)
        key = (hw[0], hw[1], 0, "cascade_stage_join", self.gru_backend,
               self.input_mode, cm, xm)
        return self._dispatch_state(
            key, lambda: self._sched_join_fn()(running, incoming, mk))

    def infer_cascade_handoff(self, hw: Tuple[int, int], state, stage,
                              slot_map: np.ndarray,
                              cheap_mode: Optional[str] = None,
                              cert_mode: Optional[str] = None):
        """The tier handoff: assemble the certified-format carried state
        (tier-independent leaves cast from the cheap ``state``, corr
        swapped in from ``stage`` — serve/cascade/handoff.py) and gather
        lanes so promoted slots land at their certified-batch slots.

        ``slot_map`` is a (max_batch_size,) int array mapping TARGET
        slot index -> SOURCE slot index (unpromoted target lanes may map
        anywhere — their rows are dead weight the follow-up
        ``infer_sched_join`` mask ignores).  Returns
        ``(state, included_compile)`` with ``state`` device-resident in
        the certified tier's trace signature."""
        slot_map = np.asarray(slot_map, np.int32)
        assert slot_map.shape == (self.cfg.max_batch_size,), slot_map.shape
        with self._device_ctx():
            idx = jnp.asarray(slot_map)
        cm, xm = self._cascade_pair(cheap_mode, cert_mode)
        key = (hw[0], hw[1], 0, "cascade_handoff", self.gru_backend,
               self.input_mode, cm, xm)
        return self._dispatch_state(
            key, lambda: self._cascade_handoff_fn(cm, xm)(state, stage,
                                                          idx))

    def infer_cascade_delta(self, hw: Tuple[int, int], prev_disp, disp,
                            cheap_mode: Optional[str] = None,
                            cert_mode: Optional[str] = None):
        """Per-slot mean |Δdisparity| between consecutive boundaries on
        the low-res grid, fetched to host — the divergence trigger's EMA
        input (serve/cascade/policy.py).  Returns ``((B,) float32,
        included_compile)``."""
        cm, xm = self._cascade_pair(cheap_mode, cert_mode)
        key = (hw[0], hw[1], 0, "cascade_delta", self.gru_backend,
               self.input_mode, cm, xm)
        (deltas,), miss = self._dispatch(
            key, lambda: [self._cascade_delta_fn()(prev_disp, disp)])
        return deltas, miss

    def warmup_cascade(self, buckets=None, iters_per_step: int = 1,
                       schedules: Sequence[object] = ()) -> List[Tuple]:
        """Compile every cascade executable — including the transition
        pair — for the configured buckets before serving, so a cascade
        request never stalls behind an XLA compile: both tiers' sched
        phases (via ``warmup_sched``), the four cascade phases, AND one
        certified step + epilogue driven from a handed-off state, so any
        signature drift between the handoff output and the certified
        trace retraces HERE, not under traffic (the retrace-budget-0
        e2e in tests/test_cascade.py holds the engine to that).

        ``schedules`` are CascadeSchedule objects or schedule strings;
        distinct (cheap, certified) mode pairs are compiled once.
        Returns the newly warmed keys."""
        from .cascade.schedule import parse_schedule
        buckets = list(buckets or self.cfg.buckets)
        parsed = [s if hasattr(s, "legs") else parse_schedule(s)
                  for s in schedules]
        mode_pairs = sorted({(s.cheap_mode, s.cert_mode) for s in parsed})
        bsz = self.cfg.max_batch_size
        warmed: List[Tuple] = []
        for cheap_mode, cert_mode in mode_pairs:
            # The cascade rides both tiers' step/epilogue executables;
            # warm them first (no-op for already-warm modes).
            warmed.extend(self.warmup_sched(buckets=buckets,
                                            iters_per_step=iters_per_step,
                                            modes=[cheap_mode, cert_mode]))
            for h, w in buckets:
                bh, bw = self.bucket_of((h, w, self.input_channels))
                if self.is_cascade_warm((bh, bw), iters_per_step,
                                        cheap_mode, cert_mode):
                    continue
                zero = np.zeros((h, w, self.input_channels), np.float32)
                t0 = time.perf_counter()
                hw, state, stage, _ = self.infer_cascade_prologue(
                    [(zero, zero)], [None], [0], cheap_mode=cheap_mode,
                    cert_mode=cert_mode)
                stage, _ = self.infer_cascade_stage_join(
                    hw, stage, stage, np.zeros(bsz, bool),
                    cheap_mode=cheap_mode, cert_mode=cert_mode)
                self.infer_cascade_delta(
                    hw, state["disp"], state["disp"],
                    cheap_mode=cheap_mode, cert_mode=cert_mode)
                state, _ = self.infer_cascade_handoff(
                    hw, state, stage, np.zeros(bsz, np.int32),
                    cheap_mode=cheap_mode, cert_mode=cert_mode)
                # The transition pair: certified step + epilogue FROM the
                # handoff output (cache hits when the handoff reproduces
                # the certified trace signature — the design contract).
                state, _ = self.infer_sched_step(hw, state, iters_per_step,
                                                 mode=cert_mode)
                self.infer_sched_epilogue(hw, state, mode=cert_mode)
                logger.info(
                    "cascade warmup: bucket %dx%d %s->%s "
                    "iters_per_step=%d compiled in %.1fs", bh, bw,
                    cheap_mode, cert_mode, iters_per_step,
                    time.perf_counter() - t0)
                warmed.extend(self._cascade_keys((bh, bw), cheap_mode,
                                                 cert_mode))
        return warmed
