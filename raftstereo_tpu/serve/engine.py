"""Shape-bucketed, padded-batch compiled inference engine.

The serving analogue of ``eval/runner.Evaluator``: one compiled executable
per (shape bucket, GRU iterations), reused across requests.  Three shape
decisions keep the XLA compile count small and predictable:

* every image is padded with the SAME ``BucketPadder`` policy the Evaluator
  uses (divis_by alignment, then round-up to ``bucket_multiple``), so
  near-identical sizes share a bucket — and per-sample numerics match the
  single-image Evaluator bitwise;
* every dispatched batch is zero-padded along the batch axis to
  ``max_batch_size``, so a bucket compiles exactly once regardless of how
  many requests the micro-batcher coalesced (padding rows are dead weight
  on the MXU but convs/norms are per-sample, so real samples are
  unaffected);
* configured buckets are compiled eagerly at startup (``warmup``), so the
  first real request never pays the multi-second XLA compile.

The engine is deliberately synchronous and lock-serialized: ordering and
batching policy live in the batcher; this layer owns shapes, compiles and
device dispatch only.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ServeConfig
from ..ops.image import BucketPadder
from .metrics import ServeMetrics

logger = logging.getLogger(__name__)

__all__ = ["BatchEngine"]


class BatchEngine:
    """Batched test-mode forward behind a shape-bucketed compile cache."""

    def __init__(self, model, variables, config: ServeConfig,
                 metrics: Optional[ServeMetrics] = None):
        self.model = model
        self.variables = variables
        self.cfg = config
        self.metrics = metrics
        self._fns: Dict[int, object] = {}  # iters -> jitted forward
        self._compiled: Set[Tuple[int, int, int]] = set()  # (h, w, iters)
        self._lock = threading.RLock()
        # Fine-grained lock for _compiled only: stat readers (/healthz)
        # must not block behind _lock, which is held across a whole device
        # dispatch (seconds) or compile (minutes).
        self._stats_lock = threading.Lock()
        self.last_batch_runtime: float = float("nan")
        self.last_included_compile: bool = True

    # ----------------------------------------------------------- shape policy

    def _padder(self, shape: Sequence[int]) -> BucketPadder:
        return BucketPadder(shape, divis_by=self.cfg.divis_by,
                            bucket_multiple=self.cfg.bucket_multiple)

    def bucket_of(self, shape: Sequence[int]) -> Tuple[int, int]:
        """The padded (H, W) an image of ``shape`` executes at."""
        return self._padder(shape).bucket_hw

    @property
    def cache_stats(self) -> Dict[str, int]:
        with self._stats_lock:  # vs a concurrent add() resizing the set
            return {"compiled": len(self._compiled)}

    @property
    def compiled_keys(self) -> Set[Tuple[int, int, int]]:
        with self._stats_lock:
            return set(self._compiled)

    def is_warm(self, hw: Tuple[int, int], iters: int) -> bool:
        """Whether (bucket, iters) already has a compiled executable."""
        with self._stats_lock:
            return (hw[0], hw[1], iters) in self._compiled

    # -------------------------------------------------------------- execution

    def _fn(self, iters: int):
        if iters not in self._fns:
            self._fns[iters] = jax.jit(
                lambda v, a, b, it=iters: self.model.forward(
                    v, a, b, iters=it, test_mode=True))
        return self._fns[iters]

    def warmup(self, buckets=None, iters_list=None) -> List[Tuple[int, int,
                                                                  int]]:
        """Compile the configured buckets before serving traffic.

        Covers both iteration levels (normal + degraded) so flipping into
        graceful degradation under load never stalls the queue behind a
        compile — exactly the moment a compile is least affordable.
        Returns the (h, w, iters) keys warmed.
        """
        buckets = list(buckets or self.cfg.buckets)
        iters_list = list(iters_list
                          or {self.cfg.iters, self.cfg.degraded_iters})
        warmed = []
        for h, w in buckets:
            bh, bw = self.bucket_of((h, w, 3))
            for iters in iters_list:
                key = (bh, bw, iters)
                if key in self._compiled:
                    continue
                zero = np.zeros((h, w, 3), np.float32)
                t0 = time.perf_counter()
                self.infer_batch([(zero, zero)], iters)
                logger.info("warmup: bucket %dx%d iters=%d compiled in %.1fs",
                            bh, bw, iters, time.perf_counter() - t0)
                warmed.append(key)
        return warmed

    def infer_batch(self, pairs: Sequence[Tuple[np.ndarray, np.ndarray]],
                    iters: int) -> List[np.ndarray]:
        """Run a coalesced batch; returns one (H, W) disparity per pair.

        All pairs must map to the same shape bucket (the batcher groups by
        bucket before dispatching).  The batch axis is zero-padded to
        ``max_batch_size`` so the compile cache is keyed by bucket alone.
        """
        assert pairs, "empty batch"
        assert len(pairs) <= self.cfg.max_batch_size, (
            f"batch {len(pairs)} exceeds max_batch_size "
            f"{self.cfg.max_batch_size}")
        padders = [self._padder(p[0].shape) for p in pairs]
        hw = padders[0].bucket_hw
        assert all(p.bucket_hw == hw for p in padders), (
            "mixed buckets in one batch: "
            f"{sorted({p.bucket_hw for p in padders})}")
        lefts, rights = [], []
        for (im1, im2), padder in zip(pairs, padders):
            i1, i2 = padder.pad(jnp.asarray(im1, jnp.float32)[None],
                                jnp.asarray(im2, jnp.float32)[None])
            lefts.append(i1)
            rights.append(i2)
        pad_rows = self.cfg.max_batch_size - len(pairs)
        i1 = jnp.concatenate(lefts, axis=0)
        i2 = jnp.concatenate(rights, axis=0)
        if pad_rows:
            i1 = jnp.pad(i1, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
            i2 = jnp.pad(i2, ((0, pad_rows), (0, 0), (0, 0), (0, 0)))
        key = (hw[0], hw[1], iters)
        with self._lock:
            with self._stats_lock:
                miss = key not in self._compiled
            if self.metrics is not None:
                (self.metrics.compile_misses if miss
                 else self.metrics.compile_hits).inc()
            start = time.perf_counter()
            _, flow_up = self._fn(iters)(self.variables, i1, i2)
            flow_up = np.asarray(flow_up, np.float32)  # host fetch = done
            self.last_batch_runtime = time.perf_counter() - start
            self.last_included_compile = miss
            with self._stats_lock:
                self._compiled.add(key)
        if self.metrics is not None and not miss:
            self.metrics.batch_latency.observe(self.last_batch_runtime)
        return [padder.unpad(flow_up[i:i + 1])[0, ..., 0]
                for i, padder in enumerate(padders)]
