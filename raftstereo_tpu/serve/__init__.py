"""Dynamic-batching inference serving subsystem (docs/serving.md).

Layers, bottom-up:

* ``engine.BatchEngine``    — shape-bucketed, padded-batch compile cache
                              around the test-mode forward, with startup
                              warmup (shares ``ops/image.BucketPadder``
                              with the Evaluator, bitwise).
* ``batcher.DynamicBatcher``— deadline-aware micro-batching, bounded-queue
                              admission control, per-request timeouts, and
                              load-adaptive GRU-iteration degradation.
* ``sched``                 — iteration-level continuous batching
                              (``--sched``): a per-request scheduler over
                              the engine's prologue/step/epilogue phase
                              executables — requests join/leave one
                              running batch per bucket at iteration
                              boundaries (priorities with anti-starvation
                              aging, deadline-aware anytime early exit,
                              no head-of-line blocking; docs/serving.md
                              "Scheduling").
* ``cascade``               — speculative tier cascades (``--cascades``):
                              schedule grammar, divergence-trigger
                              policy and the cheap-to-certified state
                              handoff — most GRU iterations on a cheap
                              precision tier, the last K on the
                              certified fp32 executables (docs/serving.md
                              "Tier cascade").
* ``metrics``               — counters / gauges / latency histograms with
                              Prometheus text exposition.
* ``server.StereoServer``   — stdlib HTTP front-end: ``/predict``,
                              ``/metrics``, ``/healthz``, ``/debug/*``
                              (per-request traces keyed by X-Request-Id,
                              on-demand XLA profile, thread dump, vars —
                              raftstereo_tpu.obs, docs/observability.md).
* ``client``                — blocking client + closed/open-loop load
                              generator.

Entry point: ``python -m raftstereo_tpu.cli.serve``; smoke benchmark:
``python bench.py --serve --quick``.

Video streams ride the same engine: ``/predict`` with ``session_id``/
``seq_no`` warm-starts each frame from the session's previous disparity
through the engine's warm-start executables (``infer_stream_batch``),
with per-stream state and the adaptive iteration ladder living in the
``raftstereo_tpu.stream`` package (docs/streaming.md).
"""

import importlib

# Lazy (PEP 562) exports: importing this package must stay cheap so the
# model-free surfaces (cli.router, serve/cluster/router.py, client-side
# tooling) never drag in the engine/model stack — ``BatchEngine`` pulls
# jax + flax + the model, which a proxy or load-gen process has no use
# for.  ``from raftstereo_tpu.serve import X`` works unchanged; the
# submodule is imported on first attribute access.
_EXPORTS = {
    "DynamicBatcher": ".batcher",
    "Future": ".batcher",
    "Overloaded": ".batcher",
    "RequestTimedOut": ".batcher",
    "ServeResult": ".batcher",
    "ShuttingDown": ".batcher",
    "ServeClient": ".client",
    "ServeError": ".client",
    "run_load": ".client",
    "synthetic_pair_pool": ".client",
    "ClusterDispatcher": ".cluster",
    "ReplicaSet": ".cluster",
    "StereoRouter": ".cluster",
    "build_router": ".cluster",
    "BatchEngine": ".engine",
    "CascadeSchedule": ".cascade",
    "cheapest": ".cascade",
    "handoff_state": ".cascade",
    "parse_schedule": ".cascade",
    "validate_schedule": ".cascade",
    "ClusterMetrics": ".metrics",
    "Counter": ".metrics",
    "Gauge": ".metrics",
    "MetricsRegistry": ".metrics",
    "ServeMetrics": ".metrics",
    "IterationScheduler": ".sched",
    "SchedResult": ".sched",
    "StereoServer": ".server",
    "build_server": ".server",
    "decode_array": ".server",
    "encode_array": ".server",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        rel = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    value = getattr(importlib.import_module(rel, __name__), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
