"""Dynamic-batching inference serving subsystem (docs/serving.md).

Layers, bottom-up:

* ``engine.BatchEngine``    — shape-bucketed, padded-batch compile cache
                              around the test-mode forward, with startup
                              warmup (shares ``ops/image.BucketPadder``
                              with the Evaluator, bitwise).
* ``batcher.DynamicBatcher``— deadline-aware micro-batching, bounded-queue
                              admission control, per-request timeouts, and
                              load-adaptive GRU-iteration degradation.
* ``sched``                 — iteration-level continuous batching
                              (``--sched``): a per-request scheduler over
                              the engine's prologue/step/epilogue phase
                              executables — requests join/leave one
                              running batch per bucket at iteration
                              boundaries (priorities with anti-starvation
                              aging, deadline-aware anytime early exit,
                              no head-of-line blocking; docs/serving.md
                              "Scheduling").
* ``metrics``               — counters / gauges / latency histograms with
                              Prometheus text exposition.
* ``server.StereoServer``   — stdlib HTTP front-end: ``/predict``,
                              ``/metrics``, ``/healthz``, ``/debug/*``
                              (per-request traces keyed by X-Request-Id,
                              on-demand XLA profile, thread dump, vars —
                              raftstereo_tpu.obs, docs/observability.md).
* ``client``                — blocking client + closed/open-loop load
                              generator.

Entry point: ``python -m raftstereo_tpu.cli.serve``; smoke benchmark:
``python bench.py --serve --quick``.

Video streams ride the same engine: ``/predict`` with ``session_id``/
``seq_no`` warm-starts each frame from the session's previous disparity
through the engine's warm-start executables (``infer_stream_batch``),
with per-stream state and the adaptive iteration ladder living in the
``raftstereo_tpu.stream`` package (docs/streaming.md).
"""

from .batcher import (  # noqa: F401
    DynamicBatcher,
    Future,
    Overloaded,
    RequestTimedOut,
    ServeResult,
    ShuttingDown,
)
from .client import (  # noqa: F401
    ServeClient,
    ServeError,
    run_load,
    synthetic_pair_pool,
)
from .engine import BatchEngine  # noqa: F401
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    MetricsRegistry,
    ServeMetrics,
)
from .sched import IterationScheduler, SchedResult  # noqa: F401
from .server import (  # noqa: F401
    StereoServer,
    build_server,
    decode_array,
    encode_array,
)
