"""Shared CLI plumbing: logging setup and weight loading.

One weight loader covers both checkpoint families so every entry point can
restore from either (the reference is .pth-only and strict,
reference: evaluate_stereo.py:215-220, demo.py:25):

* ``*.pth``          — released/reference torch checkpoints, converted on
                       load (utils/convert.py)
* anything else      — this framework's Orbax weight directories
"""

from __future__ import annotations

import logging
from typing import Dict

from ..config import RAFTStereoConfig


def setup_logging(level=logging.INFO) -> None:
    """Logging + platform bring-up shared by every CLI entry point.

    The platform re-apply is load-bearing: this image's site hook imports
    jax at interpreter startup and freezes the platform choice before a
    shell-provided ``JAX_PLATFORMS`` can act, and its accelerator fallback
    depends on tunnel availability — without the re-apply,
    ``JAX_PLATFORMS=cpu python -m raftstereo_tpu.cli.evaluate`` silently
    ran on the TPU whenever the tunnel was free (utils/platform.py).
    """
    from ..utils.platform import apply_env_platform
    apply_env_platform()
    # force=True: the platform bring-up above imports jax/absl, which can
    # leave a handler on the root logger — without force, basicConfig would
    # silently no-op and INFO-level progress ("Mesh", "Resumed from step N")
    # would never reach stderr in non-tty/subprocess runs.
    logging.basicConfig(
        level=level, force=True,
        format="%(asctime)s %(levelname)-8s [%(filename)s:%(lineno)d] %(message)s")


def load_variables(path: str, config: RAFTStereoConfig, model=None) -> Dict:
    """Restore model variables from a .pth file or an Orbax weights dir."""
    if path.endswith(".pth"):
        from ..utils.convert import convert_checkpoint
        return convert_checkpoint(path, config)
    from ..models import RAFTStereo
    from ..train.checkpoint import load_weights
    model = model or RAFTStereo(config)
    import jax
    template = model.init(jax.random.key(0))
    return load_weights(path, template)
