"""Cluster front-end: route /predict over N backend stereo servers.

Start two backends (possibly on different hosts/chips), then the router:

    python -m raftstereo_tpu.cli.serve --port 8080 ... &
    python -m raftstereo_tpu.cli.serve --port 8090 ... &
    python -m raftstereo_tpu.cli.router --port 8000 \
        --backends 127.0.0.1:8080 127.0.0.1:8090

Clients talk to the router exactly like a single server (`serve
--loadgen`, `serve/client.py`): cold requests spread over the ready
backends with failover, session frames pin to one backend — and when a
backend drains or dies the router MIGRATES the session's warm-start
state to its new home over the backends' ``/debug/sessions`` endpoints
(any backend can resume any stream).  ``GET /metrics`` exposes the
``cluster_*`` autoscaling families plus the ``ops/autoscale.py`` scale
advice.  ``POST /debug/drain`` with ``{"backend": "b0"}`` drains one
backend for maintenance/scale-in; ``POST /debug/restart`` is the
zero-downtime rolling-restart verb (drain -> warm session handoff ->
operator restarts with warmup_async -> readiness-gated rejoin).
Semantics: docs/serving.md "Cluster" and "Session migration & rolling
restart".

The router is model-free: it never imports the engine/model stack
(jax/flax/weights — the serve package exports lazily to keep it that
way), holds no device state, and starts in milliseconds.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from ..config import add_router_args, router_config_from_args
from .common import setup_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    add_router_args(p)
    return p


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)
    cfg = router_config_from_args(args)

    from ..serve.cluster import build_router

    router = build_router(cfg)
    print(json.dumps({
        "routing": f"http://{cfg.host}:{router.port}",
        "backends": [f"{h}:{p}" for h, p in cfg.backends],
        "endpoints": ["/predict", "/metrics", "/metrics/fleet", "/healthz",
                      "/debug/trace", "/debug/alerts", "/debug/threads",
                      "/debug/vars", "/debug/drain", "/debug/restart"],
    }), flush=True)
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        router.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
