"""Evaluation entry point (reference: evaluate_stereo.py:192-243).

    python -m raftstereo_tpu.cli.evaluate --dataset eth3d \
        --restore_ckpt models/raftstereo-eth3d.pth --corr_implementation reg

Accepts .pth (converted on load) or Orbax weight directories; prints the
parameter count and the benchmark's EPE/D1 dict.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from ..config import add_model_args, model_config_from_args
from ..eval import VALIDATORS, validate
from ..models import RAFTStereo
from ..models.raft_stereo import count_parameters
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights to evaluate")
    p.add_argument("--dataset", required=True, choices=sorted(VALIDATORS),
                   help="benchmark to run")
    p.add_argument("--valid_iters", type=int, default=32,
                   help="GRU refinement iterations at eval time")
    p.add_argument("--dataset_root", default=None,
                   help="override the default datasets/ root")
    p.add_argument("--max_images", type=int, default=None,
                   help="evaluate only the first N images (things only)")
    add_model_args(p)
    return p


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)
    config = model_config_from_args(args)

    import jax
    model = RAFTStereo(config)
    if args.restore_ckpt:
        variables = load_variables(args.restore_ckpt, config, model)
        logger.info("Loaded checkpoint %s", args.restore_ckpt)
    else:
        variables = model.init(jax.random.key(0))
        logger.warning("No --restore_ckpt: evaluating RANDOM weights")
    logger.info("The model has %.2fM learnable parameters.",
                count_parameters(variables) / 1e6)

    kwargs = {"iters": args.valid_iters}
    if args.dataset_root:
        kwargs["root"] = args.dataset_root
    if args.max_images is not None and args.dataset == "things":
        kwargs["max_images"] = args.max_images
    results = validate(args.dataset, model, variables, **kwargs)
    print(json.dumps(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
