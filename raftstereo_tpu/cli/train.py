"""Training entry point (reference: train_stereo.py:133-258).

    python -m raftstereo_tpu.cli.train --name raft-stereo --batch_size 8 \
        --train_datasets sceneflow --num_steps 200000 --mixed_precision

Differences from the reference by design (SURVEY.md §5, §7):

* data parallelism = batch sharding over a ``jax.sharding`` mesh; XLA emits
  the gradient all-reduce over ICI/DCN (vs ``nn.DataParallel``)
* checkpoints are full train state via Orbax (params + opt state + step), so
  ``--restore_ckpt``-less restarts resume exactly where they stopped instead
  of restarting the LR schedule; ``--restore_ckpt`` additionally accepts
  reference ``.pth`` files (converted on load) for fine-tuning
* the whole step (fwd + loss + bwd + clip + update) is one jitted program
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

from ..config import TrainConfig, add_model_args, model_config_from_args
from ..data.datasets import (build_aug_params, fetch_dataset,
                             take_photometric_params)
from ..data.loader import DataLoader, prefetch_to_device
from ..eval import validate_things
from ..models import RAFTStereo
from ..models.raft_stereo import count_parameters
from ..parallel import batch_sharded, make_mesh
from ..train.checkpoint import CheckpointManager, save_weights
from ..train.logger import Logger
from ..train.optim import make_optimizer
from ..train.state import create_train_state, state_from_variables
from ..train.step import jit_train_step, make_train_step
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def add_train_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("training")
    g.add_argument("--name", default="raft-stereo")
    g.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights to start from")
    g.add_argument("--batch_size", type=int, default=6)
    g.add_argument("--train_datasets", nargs="+", default=["sceneflow"])
    g.add_argument("--lr", type=float, default=2e-4)
    g.add_argument("--num_steps", type=int, default=100000)
    g.add_argument("--image_size", type=int, nargs=2, default=[320, 720])
    g.add_argument("--train_iters", type=int, default=16)
    g.add_argument("--valid_iters", type=int, default=32)
    g.add_argument("--wdecay", type=float, default=1e-5)
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--validation_frequency", type=int, default=10000)
    g.add_argument("--checkpoint_dir", default="checkpoints")
    g.add_argument("--dataset_root", default=None)
    g.add_argument("--data_parallel", type=int, default=None,
                   help="devices on the data mesh axis (default: all)")
    g.add_argument("--num_workers", type=int, default=None)
    g.add_argument("--no_validation", action="store_true",
                   help="skip the periodic FlyingThings validation")
    g.add_argument("--profile_steps", type=int, nargs=2, default=None,
                   metavar=("START", "STOP"),
                   help="capture an XLA profiler trace of steps [START, STOP)"
                        " into runs/<name>/profile (view in TensorBoard)")
    g.add_argument("--nan_policy", choices=["abort", "skip"], default="abort",
                   help="non-finite loss/grad: abort (reference assert "
                        "semantics) or skip the update and continue")
    g.add_argument("--max_restarts", type=int, default=0,
                   help="auto-restart the loop from the latest checkpoint "
                        "this many times after a crash (elastic recovery)")
    a = p.add_argument_group("augmentation (reference: train_stereo.py:244-248)")
    a.add_argument("--img_gamma", type=float, nargs="+", default=None,
                   help="gamma range: GMIN GMAX [GAIN_MIN GAIN_MAX] "
                        "(reference: train_stereo.py:244)")
    a.add_argument("--saturation_range", type=float, nargs=2, default=None)
    a.add_argument("--do_flip", choices=["h", "v"], default=None)
    a.add_argument("--spatial_scale", type=float, nargs=2, default=[0.0, 0.0])
    a.add_argument("--noyjitter", action="store_true")
    a.add_argument("--device_photometric", action="store_true",
                   help="run the photometric chain (jitter + eraser) "
                        "on-device inside the jitted train step instead of "
                        "in host workers — for CPU-starved hosts "
                        "(data/device_aug.py)")


def train_config_from_args(args: argparse.Namespace) -> TrainConfig:
    return TrainConfig(
        name=args.name, batch_size=args.batch_size,
        train_datasets=tuple(args.train_datasets), lr=args.lr,
        num_steps=args.num_steps, image_size=tuple(args.image_size),
        train_iters=args.train_iters, valid_iters=args.valid_iters,
        wdecay=args.wdecay, seed=args.seed,
        validation_frequency=args.validation_frequency,
        checkpoint_dir=args.checkpoint_dir, restore_ckpt=args.restore_ckpt,
        img_gamma=args.img_gamma, saturation_range=args.saturation_range,
        do_flip=args.do_flip, spatial_scale=tuple(args.spatial_scale),
        noyjitter=args.noyjitter, data_parallel=args.data_parallel,
        nan_policy=args.nan_policy, max_restarts=args.max_restarts,
        device_photometric=args.device_photometric)


def train(model_cfg, cfg: TrainConfig, dataset=None,
          num_workers=None, no_validation: bool = False,
          dataset_root=None, profile_steps=None) -> "TrainState":  # noqa: F821
    """The training loop; returns the final state.  ``dataset`` injection
    lets tests run the full loop on synthetic data."""
    import jax

    np.random.seed(cfg.seed)

    model = RAFTStereo(model_cfg)
    tx, schedule = make_optimizer(cfg)
    mesh = make_mesh(data=cfg.data_parallel)
    n_data = mesh.shape["data"]
    if cfg.batch_size % n_data:
        raise ValueError(f"batch_size {cfg.batch_size} not divisible by "
                         f"{n_data} data-parallel devices")
    logger.info("Mesh: %s", dict(mesh.shape))

    ckpt_dir = os.path.join(cfg.checkpoint_dir, cfg.name)
    manager = CheckpointManager(ckpt_dir, keep=cfg.keep_checkpoints)

    def init_state():
        """Latest checkpoint > --restore_ckpt weights > fresh init.  Also the
        recovery path after a crash (--max_restarts)."""
        state = create_train_state(model, jax.random.key(cfg.seed), tx,
                                   image_hw=cfg.image_size)
        if manager.latest_step() is not None:
            state = manager.restore(state)
            logger.info("Resumed from step %d in %s", int(state.step),
                        ckpt_dir)
        elif cfg.restore_ckpt:
            variables = load_variables(cfg.restore_ckpt, model_cfg, model)
            state = state_from_variables(variables, tx)
            logger.info("Initialised weights from %s", cfg.restore_ckpt)
        return state

    state = init_state()
    logger.info("The model has %.2fM learnable parameters.",
                count_parameters({"params": state.params}) / 1e6)

    if dataset is None:
        aug = build_aug_params(cfg.image_size, cfg.spatial_scale,
                               cfg.noyjitter, cfg.saturation_range,
                               cfg.img_gamma, cfg.do_flip)
        roots = ({k: dataset_root for k in
                  ("sceneflow", "kitti", "middlebury", "sintel",
                   "falling_things", "tartanair", "sl")}
                 if dataset_root else None)
        dataset = fetch_dataset(cfg.train_datasets, aug, roots)
    photometric_params = None
    if cfg.device_photometric:
        # Disables host jitter+eraser on EVERY leaf (including
        # caller-supplied datasets — otherwise they'd be augmented twice)
        # and mirrors the host augmentors' exact parameter set on-device.
        photometric_params = take_photometric_params(dataset)
        logger.info("Photometric augmentation on-device "
                    "(--device_photometric): %s", photometric_params)
    loader = DataLoader(dataset, cfg.batch_size, shuffle=True, drop_last=True,
                        num_workers=num_workers, seed=cfg.seed)
    logger.info("Train loader: %d samples, %d batches/epoch",
                len(dataset), len(loader))
    if len(loader) == 0:
        raise ValueError(
            f"empty train loader: {len(dataset)} samples < batch_size "
            f"{cfg.batch_size} (check --train_datasets/--dataset_root)")

    # Fail fast if the periodic regression check can't run (reference runs
    # validate_things every 10k steps, train_stereo.py:184-191; silently
    # skipping it would let a training run go fully unchecked).  Probing at
    # startup also means the validation dataset is built exactly once.
    val_dataset = None
    if not no_validation:
        from ..data import datasets as ds
        try:
            val_dataset = ds.SceneFlowDatasets(
                aug_params=None, dstype="frames_finalpass", things_test=True,
                **({"root": dataset_root} if dataset_root else {}))
        except Exception as e:
            raise ValueError(
                "in-training validation requires the FlyingThings3D TEST "
                f"split and it could not be loaded ({e}); fix the dataset "
                "root or pass --no_validation to opt out explicitly") from e
        if len(val_dataset) == 0:
            raise ValueError(
                "in-training validation dataset is empty; fix the dataset "
                "root or pass --no_validation to opt out explicitly")

    step_fn = jit_train_step(
        make_train_step(model, tx, cfg, schedule,
                        photometric_params=photometric_params), mesh)
    metrics_logger = Logger(log_dir=os.path.join("runs", cfg.name),
                            total_steps=int(state.step))
    from ..utils.profiling import StepProfiler
    prof = StepProfiler(os.path.join("runs", cfg.name, "profile"),
                        *(profile_steps or (-1, -1)))

    def maybe_validate(state):
        if no_validation:
            return
        try:
            results = validate_things(
                model, state.variables, iters=cfg.valid_iters,
                dataset=val_dataset, max_images=200)
        except Exception as e:
            # Startup probed the dataset, so this is a genuine runtime
            # failure — make it loud and countable, not a silent skip.
            logger.error("Validation FAILED (counted as "
                         "validation_skipped): %s", e)
            metrics_logger.push({"validation_skipped": 1.0})
            return
        metrics_logger.push({"validation_skipped": 0.0})
        logger.info("Validation: %s", results)
        metrics_logger.write_dict(results)

    def run_loop(state):
        total_steps = int(state.step)
        should_keep_training = total_steps <= cfg.num_steps
        while should_keep_training:
            # Prefetch: the host->HBM copy (and mesh sharding) of the next
            # batch overlaps the current step's compute — the TPU analogue
            # of the reference's pin_memory loader (core/stereo_datasets.py:311).
            for batch in prefetch_to_device(loader, size=2,
                                            devices=batch_sharded(mesh)):
                with prof.step(total_steps):
                    state, metrics = step_fn(state, batch)
                total_steps += 1
                metrics = {k: float(v) for k, v in metrics.items()}
                if metrics.pop("nonfinite", 0.0) >= 0.5:
                    if cfg.nan_policy == "abort":
                        # Reference assert semantics (train_stereo.py:49-52).
                        raise FloatingPointError(
                            f"non-finite loss/gradient at step {total_steps}")
                    logger.warning("step %d: non-finite loss/gradient — "
                                   "update skipped", total_steps)
                    # Don't push the NaN metrics: one skipped step would turn
                    # the whole running-mean window NaN.  Record the skip.
                    metrics_logger.push({"skipped": 1.0})
                else:
                    metrics["skipped"] = 0.0
                    metrics_logger.write_scalar("live_loss",
                                                metrics.get("loss", 0.0),
                                                total_steps)
                    if "lr" in metrics:
                        metrics_logger.write_scalar("lr", metrics["lr"],
                                                    total_steps)
                    metrics_logger.push(metrics)

                if total_steps % cfg.validation_frequency == 0:
                    manager.save(total_steps, state)
                    maybe_validate(state)

                if total_steps > cfg.num_steps:
                    should_keep_training = False
                    break

            # Per-epoch checkpoint for very long epochs
            # (reference: train_stereo.py:202-205).
            if len(loader) >= 10000:
                manager.save(total_steps, state)
        return state

    restarts = 0
    try:
        while True:
            try:
                state = run_loop(state)
                break
            except (KeyboardInterrupt, FloatingPointError):
                # FloatingPointError = nan_policy abort: deterministic given
                # the data — replaying from a checkpoint would hit it again.
                raise
            except Exception as e:
                # Elastic recovery: resume from the latest checkpoint
                # (the reference's only recovery is a manual restart with
                # --restore_ckpt, train_stereo.py:143-148).
                if restarts >= cfg.max_restarts:
                    raise
                restarts += 1
                logger.warning("training loop failed (%s); restart %d/%d",
                               e, restarts, cfg.max_restarts)
                state = init_state()
                logger.info("restarted at step %d", int(state.step))
    finally:
        # Flush any in-flight profiler trace even when the loop dies between
        # profiled steps (the step-internal handler only covers exceptions
        # raised inside the step itself).
        prof.close()

    manager.save(int(state.step), state, wait=True)
    final = os.path.join(ckpt_dir, f"{cfg.name}-final")
    save_weights(final, state.variables)
    logger.info("Saved final weights to %s", final)
    metrics_logger.close()
    manager.close()
    return state


def main(argv=None) -> int:
    setup_logging()
    p = argparse.ArgumentParser(description=__doc__)
    add_train_args(p)
    add_model_args(p)
    args = p.parse_args(argv)
    train(model_config_from_args(args), train_config_from_args(args),
          num_workers=args.num_workers, no_validation=args.no_validation,
          dataset_root=args.dataset_root, profile_steps=args.profile_steps)
    return 0


if __name__ == "__main__":
    sys.exit(main())
