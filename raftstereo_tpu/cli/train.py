"""Training entry point (reference: train_stereo.py:133-258).

    python -m raftstereo_tpu.cli.train --name raft-stereo --batch_size 8 \
        --train_datasets sceneflow --num_steps 200000 --mixed_precision

Differences from the reference by design (SURVEY.md §5, §7):

* data parallelism = batch sharding over a ``jax.sharding`` mesh; XLA emits
  the gradient all-reduce over ICI/DCN (vs ``nn.DataParallel``)
* checkpoints are full train state via Orbax (params + opt state + step), so
  ``--restore_ckpt``-less restarts resume exactly where they stopped instead
  of restarting the LR schedule; ``--restore_ckpt`` additionally accepts
  reference ``.pth`` files (converted on load) for fine-tuning
* the whole step (fwd + loss + bwd + clip + update) is one jitted program
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import logging
import os
import statistics
import sys
import time

import numpy as np

from ..config import TrainConfig, add_model_args, model_config_from_args
from ..data.datasets import (build_aug_params, fetch_dataset,
                             take_photometric_params)
from ..data.loader import DataLoader, prefetch_to_device
from ..eval import validate_things
from ..eval.validate import validate_sl
from ..models import RAFTStereo
from ..models.raft_stereo import count_parameters
from ..parallel import batch_sharded, make_mesh, replicated
from ..train.checkpoint import CheckpointManager, PreemptionGuard, save_weights
from ..train.logger import Logger
from ..train.optim import make_optimizer
from ..train.state import create_train_state, state_from_variables
from ..train.step import jit_train_step, make_train_step
from ..utils.faults import FaultPlan
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def add_train_args(p: argparse.ArgumentParser) -> None:
    g = p.add_argument_group("training")
    g.add_argument("--name", default="raft-stereo")
    g.add_argument("--workload", choices=["passive", "sl"],
                   default="passive",
                   help="training workload: passive stereo (the default "
                        "pipeline, unchanged) or structured light — "
                        "pattern-conditioned 12-channel inputs with the "
                        "masked sequence loss over the valid-modulation "
                        "region (requires --input_mode sl; "
                        "docs/structured_light.md)")
    g.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights to start from")
    g.add_argument("--batch_size", type=int, default=6)
    g.add_argument("--train_datasets", nargs="+", default=["sceneflow"])
    g.add_argument("--lr", type=float, default=2e-4)
    g.add_argument("--num_steps", type=int, default=100000)
    g.add_argument("--image_size", type=int, nargs=2, default=[320, 720])
    g.add_argument("--train_iters", type=int, default=16)
    g.add_argument("--valid_iters", type=int, default=32)
    g.add_argument("--wdecay", type=float, default=1e-5)
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--validation_frequency", type=int, default=10000)
    g.add_argument("--checkpoint_dir", default="checkpoints")
    g.add_argument("--dataset_root", default=None)
    g.add_argument("--data_parallel", type=int, default=None,
                   help="devices on the data mesh axis (default: all)")
    g.add_argument("--num_workers", type=int, default=None)
    g.add_argument("--no_validation", action="store_true",
                   help="skip the periodic FlyingThings validation")
    g.add_argument("--profile_steps", type=int, nargs=2, default=None,
                   metavar=("START", "STOP"),
                   help="capture an XLA profiler trace of steps [START, STOP)"
                        " into runs/<name>/profile (view in TensorBoard)")
    g.add_argument("--metrics_port", type=int, default=None,
                   help="serve train telemetry over HTTP on this port "
                        "(/metrics Prometheus scrape, /debug/trace span "
                        "export, /debug/threads, /debug/vars) so long runs "
                        "are observable without the JSONL file; 0 binds an "
                        "ephemeral port (docs/observability.md)")
    g.add_argument("--metrics_host", default="127.0.0.1",
                   help="interface the telemetry exporter binds; the "
                        "default stays loopback-only because /debug/threads "
                        "and /debug/vars expose stacks and resolved paths "
                        "— set 0.0.0.0 deliberately for a remote scraper")
    g.add_argument("--nan_policy", choices=["abort", "skip"], default="abort",
                   help="non-finite loss/grad: abort (reference assert "
                        "semantics) or skip the update and continue")
    g.add_argument("--max_restarts", type=int, default=0,
                   help="auto-restart the loop from the latest checkpoint "
                        "after a crash (elastic recovery); only restarts "
                        "without step progress count against the budget")
    g.add_argument("--restart_backoff", type=float, default=1.0,
                   help="base seconds between restarts (doubles per "
                        "consecutive no-progress restart, capped at 60)")
    g.add_argument("--sample_retries", type=int, default=2,
                   help="per-sample load retries (with backoff) before an "
                        "index is quarantined and resampled")
    g.add_argument("--quarantine_limit", type=int, default=64,
                   help="max persistently-bad dataset indices to quarantine "
                        "before the loader declares the dataset broken")
    g.add_argument("--loader_timeout_s", type=float, default=300.0,
                   help="seconds to wait for a worker batch before the "
                        "worker pool is recycled (0 disables)")
    g.add_argument("--watchdog_factor", type=float, default=10.0,
                   help="flag steps slower than this multiple of the "
                        "running median step time (0 disables)")
    g.add_argument("--faults", default=None,
                   help="deterministic fault-injection plan (chaos testing; "
                        "see utils/faults.py), e.g. 'crash@step=7,"
                        "corrupt@sample=3'; defaults to $RAFTSTEREO_FAULTS")
    a = p.add_argument_group("augmentation (reference: train_stereo.py:244-248)")
    a.add_argument("--img_gamma", type=float, nargs="+", default=None,
                   help="gamma range: GMIN GMAX [GAIN_MIN GAIN_MAX] "
                        "(reference: train_stereo.py:244)")
    a.add_argument("--saturation_range", type=float, nargs=2, default=None)
    a.add_argument("--do_flip", choices=["h", "v"], default=None)
    a.add_argument("--spatial_scale", type=float, nargs=2, default=[0.0, 0.0])
    a.add_argument("--noyjitter", action="store_true")
    a.add_argument("--device_photometric", action="store_true",
                   help="run the photometric chain (jitter + eraser) "
                        "on-device inside the jitted train step instead of "
                        "in host workers — for CPU-starved hosts "
                        "(data/device_aug.py)")


def train_config_from_args(args: argparse.Namespace) -> TrainConfig:
    return TrainConfig(
        name=args.name, batch_size=args.batch_size,
        train_datasets=tuple(args.train_datasets), lr=args.lr,
        num_steps=args.num_steps, image_size=tuple(args.image_size),
        train_iters=args.train_iters, valid_iters=args.valid_iters,
        wdecay=args.wdecay, seed=args.seed,
        validation_frequency=args.validation_frequency,
        checkpoint_dir=args.checkpoint_dir, restore_ckpt=args.restore_ckpt,
        img_gamma=args.img_gamma, saturation_range=args.saturation_range,
        do_flip=args.do_flip, spatial_scale=tuple(args.spatial_scale),
        noyjitter=args.noyjitter, data_parallel=args.data_parallel,
        nan_policy=args.nan_policy, max_restarts=args.max_restarts,
        restart_backoff=args.restart_backoff,
        sample_retries=args.sample_retries,
        quarantine_limit=args.quarantine_limit,
        loader_timeout_s=args.loader_timeout_s,
        watchdog_factor=args.watchdog_factor,
        device_photometric=args.device_photometric)


def train(model_cfg, cfg: TrainConfig, dataset=None,
          num_workers=None, no_validation: bool = False,
          dataset_root=None, profile_steps=None,
          fault_plan=None, metrics_port=None,
          metrics_host="127.0.0.1",
          workload: str = "passive") -> "TrainState":  # noqa: F821
    """The training loop; returns the final state.  ``dataset`` injection
    lets tests run the full loop on synthetic data; ``fault_plan``
    (default: the ``RAFTSTEREO_FAULTS`` env var) injects deterministic
    failures for chaos testing (utils/faults.py).  ``metrics_port`` mounts
    the opt-in telemetry exporter (obs/, docs/observability.md).
    ``workload`` selects the data/validation recipe: "passive" (default,
    unchanged) or "sl" — structured-light training with the modulation
    gate folded into the loss's ``valid`` mask (docs/structured_light.md);
    the loss itself is the standard masked sequence loss either way."""
    import jax

    from ..obs import Tracer, TelemetryServer
    from ..train.telemetry import TrainMetrics

    if workload not in ("passive", "sl"):
        raise ValueError(f"unknown workload {workload!r}")
    if (workload == "sl") != (model_cfg.input_mode == "sl"):
        # A passive model cannot consume 12-channel SL stacks and an SL
        # model cannot consume RGB pairs — catching it here beats a shape
        # error three layers down in the first jitted step.
        raise ValueError(
            f"workload {workload!r} requires a matching model input mode, "
            f"got input_mode={model_cfg.input_mode!r} (pass --workload sl "
            f"together with --input_mode sl)")

    np.random.seed(cfg.seed)
    plan = FaultPlan.from_env() if fault_plan is None else fault_plan
    guard = PreemptionGuard().install()

    # Always-on phase tracing (bounded ring, microseconds per span) +
    # the metrics bundle; the HTTP exporter mounts later, once setup has
    # validated (starting it here would leak the socket when e.g. the
    # batch-size/mesh check below raises before the loop's finally).
    tracer = Tracer(capacity=4096)
    tmetrics = TrainMetrics()
    run_trace = tracer.new_trace_id()
    telemetry = None

    model = RAFTStereo(model_cfg)
    tx, schedule = make_optimizer(cfg)
    mesh = make_mesh(data=cfg.data_parallel)
    n_data = mesh.shape["data"]
    if cfg.batch_size % n_data:
        raise ValueError(f"batch_size {cfg.batch_size} not divisible by "
                         f"{n_data} data-parallel devices")
    logger.info("Mesh: %s", dict(mesh.shape))

    ckpt_dir = os.path.join(cfg.checkpoint_dir, cfg.name)
    manager = CheckpointManager(ckpt_dir, keep=cfg.keep_checkpoints,
                                fault_plan=plan)

    def init_state():
        """Latest VALID checkpoint > --restore_ckpt weights > fresh init.
        Also the recovery path after a crash (--max_restarts); a corrupt
        latest step falls back to older retained steps instead of
        re-restoring the same broken step forever."""
        state = create_train_state(model, jax.random.key(cfg.seed), tx,
                                   image_hw=cfg.image_size)
        if manager.latest_step() is not None:
            restored, step = manager.restore_latest_valid(state)
            if restored is not None:
                # Rebuild the restored leaves as device arrays that OWN
                # their buffers (host round-trip + explicit placement on
                # the mesh): orbax-restored arrays can alias restore-path
                # memory, and the train step DONATES its input state — on
                # this container donating them into a compile-cache
                # deserialized executable is a use-after-free crash.
                restored = jax.device_put(
                    jax.tree.map(np.asarray, restored), replicated(mesh))
                if step != manager.latest_step():
                    logger.error(
                        "latest checkpoint (step %d) is corrupt; resumed "
                        "from retained step %d instead — up to %d steps of "
                        "work will be recomputed",
                        manager.latest_step(), step,
                        manager.latest_step() - step)
                state = restored
                logger.info("Resumed from step %d in %s", int(state.step),
                            ckpt_dir)
                return state
            logger.error("every retained checkpoint in %s is corrupt — "
                         "falling back to %s", ckpt_dir,
                         cfg.restore_ckpt or "a fresh init")
        if cfg.restore_ckpt:
            variables = load_variables(cfg.restore_ckpt, model_cfg, model)
            state = state_from_variables(variables, tx)
            logger.info("Initialised weights from %s", cfg.restore_ckpt)
        return state

    state = init_state()
    logger.info("The model has %.2fM learnable parameters.",
                count_parameters({"params": state.params}) / 1e6)

    if dataset is None:
        if workload == "sl":
            # SL trains from the capture-tree reader + the train view that
            # stacks pattern channels and folds the modulation gate into
            # ``valid`` (sl/adapter.py).  No photometric augmentation by
            # design: it would decorrelate the ambient images from the
            # pattern masks the projector physically produced.
            if not dataset_root:
                raise ValueError(
                    "--workload sl needs --dataset_root pointing at an SL "
                    "capture tree (data/sl.py layout; "
                    "sl.make_learnable_sl writes a synthetic one)")
            from ..data.sl import StructuredLightDataset
            from ..sl import SLTrainView
            dataset = SLTrainView(
                StructuredLightDataset(dataset_root, split="training",
                                       scale=1.0, with_depth=True),
                crop_size=cfg.image_size)
        else:
            aug = build_aug_params(cfg.image_size, cfg.spatial_scale,
                                   cfg.noyjitter, cfg.saturation_range,
                                   cfg.img_gamma, cfg.do_flip)
            roots = ({k: dataset_root for k in
                      ("sceneflow", "kitti", "middlebury", "sintel",
                       "falling_things", "tartanair", "sl")}
                     if dataset_root else None)
            dataset = fetch_dataset(cfg.train_datasets, aug, roots)
    photometric_params = None
    if cfg.device_photometric:
        # Disables host jitter+eraser on EVERY leaf (including
        # caller-supplied datasets — otherwise they'd be augmented twice)
        # and mirrors the host augmentors' exact parameter set on-device.
        photometric_params = take_photometric_params(dataset)
        logger.info("Photometric augmentation on-device "
                    "(--device_photometric): %s", photometric_params)
    loader = DataLoader(dataset, cfg.batch_size, shuffle=True, drop_last=True,
                        num_workers=num_workers, seed=cfg.seed,
                        sample_retries=cfg.sample_retries,
                        quarantine_limit=cfg.quarantine_limit,
                        batch_timeout=cfg.loader_timeout_s or None,
                        fault_plan=plan)
    logger.info("Train loader: %d samples, %d batches/epoch",
                len(dataset), len(loader))
    if len(loader) == 0:
        raise ValueError(
            f"empty train loader: {len(dataset)} samples < batch_size "
            f"{cfg.batch_size} (check --train_datasets/--dataset_root)")

    # Fail fast if the periodic regression check can't run (reference runs
    # validate_things every 10k steps, train_stereo.py:184-191; silently
    # skipping it would let a training run go fully unchecked).  Probing at
    # startup also means the validation dataset is built exactly once.
    val_dataset = None
    if not no_validation and workload == "sl":
        from ..data.sl import StructuredLightDataset
        from ..sl import SLTrainView
        try:
            val_dataset = SLTrainView(StructuredLightDataset(
                dataset_root, split="validation", scale=1.0,
                with_depth=True))
        except Exception as e:
            raise ValueError(
                "in-training SL validation requires the capture tree's "
                f"validation split and it could not be loaded ({e}); fix "
                "--dataset_root or pass --no_validation to opt out "
                "explicitly") from e
        if len(val_dataset) == 0:
            raise ValueError(
                "in-training SL validation dataset is empty; fix "
                "--dataset_root or pass --no_validation to opt out "
                "explicitly")
    elif not no_validation:
        from ..data import datasets as ds
        try:
            val_dataset = ds.SceneFlowDatasets(
                aug_params=None, dstype="frames_finalpass", things_test=True,
                **({"root": dataset_root} if dataset_root else {}))
        except Exception as e:
            raise ValueError(
                "in-training validation requires the FlyingThings3D TEST "
                f"split and it could not be loaded ({e}); fix the dataset "
                "root or pass --no_validation to opt out explicitly") from e
        if len(val_dataset) == 0:
            raise ValueError(
                "in-training validation dataset is empty; fix the dataset "
                "root or pass --no_validation to opt out explicitly")

    step_fn = jit_train_step(
        make_train_step(model, tx, cfg, schedule,
                        photometric_params=photometric_params), mesh)
    metrics_logger = Logger(log_dir=os.path.join("runs", cfg.name),
                            total_steps=int(state.step))
    from ..utils.profiling import StepProfiler
    prof = StepProfiler(os.path.join("runs", cfg.name, "profile"),
                        *(profile_steps or (-1, -1)))

    def maybe_validate(state):
        if no_validation:
            return
        try:
            validator = validate_sl if workload == "sl" else validate_things
            results = validator(
                model, state.variables, iters=cfg.valid_iters,
                dataset=val_dataset, max_images=200)
        except Exception as e:
            # Startup probed the dataset, so this is a genuine runtime
            # failure — make it loud and countable, not a silent skip.
            logger.error("Validation FAILED (counted as "
                         "validation_skipped): %s", e)
            metrics_logger.push({"validation_skipped": 1.0})
            return
        metrics_logger.push({"validation_skipped": 0.0})
        logger.info("Validation: %s", results)
        metrics_logger.write_dict(results)

    # Steps saved BY THIS PROCESS — the dedup key for boundary/final saves.
    # Comparing against manager.latest_step() instead would conflate "we
    # already saved this step" with "a (possibly corrupt, fallback-skipped)
    # step of that number exists on disk" and silently skip the save.
    saved_steps = set()

    def save_ckpt(step, state, wait=False):
        t0 = time.perf_counter()
        manager.save(step, state, wait=wait)
        t1 = time.perf_counter()
        # wait=False saves measure the async dispatch; wait=True (boundary
        # and final saves) the full write.
        tracer.record("checkpoint", t0, t1, run_trace,
                      attrs={"step": step, "wait": wait})
        tmetrics.checkpoint_seconds.observe(t1 - t0)
        saved_steps.add(step)

    def save_boundary(step, state):
        """Preemption save: idempotent when a periodic save already covered
        this exact step in this process."""
        if step not in saved_steps:
            save_ckpt(step, state, wait=True)

    step_times = collections.deque(maxlen=101)

    def watchdog(dt, total_steps):
        """Flag a device step that took a configurable multiple of the
        running median wall-clock (a hung collective / stuck host looks
        exactly like this before it looks like anything else)."""
        flagged = 0.0
        if (cfg.watchdog_factor > 0 and len(step_times) >= 5
                and dt > cfg.watchdog_factor * statistics.median(step_times)):
            flagged = 1.0
            logger.warning(
                "step watchdog: step %d took %.2fs (> %gx the running "
                "median %.3fs over %d steps)", total_steps, dt,
                cfg.watchdog_factor, statistics.median(step_times),
                len(step_times))
        step_times.append(dt)
        return flagged

    _EPOCH_DONE = object()

    def run_loop(state):
        """Returns (state, preempted)."""
        total_steps = int(state.step)
        should_keep_training = total_steps <= cfg.num_steps
        while should_keep_training:
            # Prefetch: the host->HBM copy (and mesh sharding) of the next
            # batch overlaps the current step's compute — the TPU analogue
            # of the reference's pin_memory loader (core/stereo_datasets.py:311).
            batches = iter(prefetch_to_device(loader, size=2,
                                              devices=batch_sharded(mesh)))
            while True:
                # Explicit next(): the wait for the prefetched batch IS the
                # data-starvation signal (span + train_data_wait_seconds).
                t_d0 = time.perf_counter()
                batch = next(batches, _EPOCH_DONE)
                t_d1 = time.perf_counter()
                if batch is _EPOCH_DONE:
                    break
                tracer.record("data_wait", t_d0, t_d1, run_trace,
                              attrs={"step": total_steps + 1})
                # The watchdog clock starts before the fault hooks so an
                # injected slow@step is measured like a real stall.
                t0 = time.monotonic()
                if plan:
                    # Deterministic chaos hooks for step total_steps+1: may
                    # sleep (slow), SIGTERM ourselves (preempt), raise
                    # (crash), or ask for a poisoned batch (nan).
                    fired = plan.at_step(total_steps + 1)
                    if "nan" in fired:
                        img1 = jax.numpy.asarray(batch[0])
                        batch = (img1.at[(0,) * img1.ndim]
                                 .set(jax.numpy.nan),) + tuple(batch[1:])
                if guard.requested:
                    # Preemption (SIGTERM/SIGINT): save at this step boundary
                    # and exit cleanly inside the grace period.
                    save_boundary(total_steps, state)
                    logger.warning(
                        "preemption: checkpoint at step %d written; exiting "
                        "cleanly", total_steps)
                    return state, True
                in_xla_window = (prof.enabled
                                 and prof.start <= total_steps < prof.stop)
                t_s0 = time.perf_counter()
                with prof.step(total_steps):
                    state, metrics = step_fn(state, batch)
                total_steps += 1
                # float() blocks on the device result, so dt covers the
                # actual step execution, not just its dispatch.
                metrics = {k: float(v) for k, v in metrics.items()}
                t_s1 = time.perf_counter()
                # xla_profile cross-references this span with the
                # StepProfiler capture it overlapped, so the host-side
                # phase trace and the XLA device trace line up in Perfetto.
                tracer.record("step", t_s0, t_s1, run_trace,
                              attrs={"step": total_steps,
                                     "xla_profile": in_xla_window})
                tmetrics.observe_step(step_s=t_s1 - t_s0,
                                      data_s=t_d1 - t_d0)
                health = loader.health_metrics()
                health["watchdog_slow"] = watchdog(time.monotonic() - t0,
                                                   total_steps)
                tmetrics.observe_health(health)
                if metrics.pop("nonfinite", 0.0) >= 0.5:
                    if cfg.nan_policy == "abort":
                        # Reference assert semantics (train_stereo.py:49-52).
                        raise FloatingPointError(
                            f"non-finite loss/gradient at step {total_steps}")
                    logger.warning("step %d: non-finite loss/gradient — "
                                   "update skipped", total_steps)
                    tmetrics.skipped.inc()
                    # Don't push the NaN metrics: one skipped step would turn
                    # the whole running-mean window NaN.  Record the skip.
                    metrics_logger.push({"skipped": 1.0, **health})
                else:
                    metrics["skipped"] = 0.0
                    metrics_logger.write_scalar("live_loss",
                                                metrics.get("loss", 0.0),
                                                total_steps)
                    if "lr" in metrics:
                        metrics_logger.write_scalar("lr", metrics["lr"],
                                                    total_steps)
                    metrics_logger.push({**metrics, **health})

                if total_steps % cfg.validation_frequency == 0:
                    save_ckpt(total_steps, state)
                    maybe_validate(state)

                if total_steps > cfg.num_steps:
                    should_keep_training = False
                    break

            # Per-epoch checkpoint for very long epochs
            # (reference: train_stereo.py:202-205).
            if len(loader) >= 10000 and total_steps not in saved_steps:
                save_ckpt(total_steps, state)
        return state, False

    # Elastic recovery: resume from the latest valid checkpoint (the
    # reference's only recovery is a manual restart with --restore_ckpt,
    # train_stereo.py:143-148).  Only restarts WITHOUT step progress count
    # against max_restarts, and consecutive no-progress restarts back off
    # exponentially, so a crash loop can't thrash the pod.
    preempted = False
    restarts_np = 0
    last_resume_step = int(state.step)
    if metrics_port is not None:
        telemetry = TelemetryServer(
            tmetrics.registry, tracer,
            vars_fn=lambda: {"config": dataclasses.asdict(cfg),
                             "model_config": dataclasses.asdict(model_cfg)},
            host=metrics_host, port=metrics_port).start()
        logger.info("telemetry exporter on %s:%d", metrics_host,
                    telemetry.port)
    try:
        while True:
            try:
                state, preempted = run_loop(state)
                break
            except (KeyboardInterrupt, FloatingPointError):
                # FloatingPointError = nan_policy abort: deterministic given
                # the data — replaying from a checkpoint would hit it again.
                raise
            except Exception as e:
                if cfg.max_restarts <= 0:
                    raise
                state = init_state()
                resume_step = int(state.step)
                if resume_step > last_resume_step:
                    # Progress since the previous restart: this one is free
                    # and the no-progress budget resets in full.
                    restarts_np = 0
                    delay = min(cfg.restart_backoff, 60.0)
                    logger.warning(
                        "training loop failed (%s); restarting after "
                        "progress (resuming at step %d, no-progress budget "
                        "reset to %d) after %.1fs backoff",
                        e, resume_step, cfg.max_restarts, delay)
                else:
                    restarts_np += 1
                    if restarts_np > cfg.max_restarts:
                        raise
                    delay = min(cfg.restart_backoff * 2 ** (restarts_np - 1),
                                60.0)
                    logger.warning(
                        "training loop failed (%s); restart %d/%d without "
                        "progress, resuming at step %d after %.1fs backoff",
                        e, restarts_np, cfg.max_restarts, resume_step, delay)
                last_resume_step = resume_step
                time.sleep(delay)
    finally:
        # Flush any in-flight profiler trace even when the loop dies between
        # profiled steps (the step-internal handler only covers exceptions
        # raised inside the step itself).
        prof.close()
        guard.uninstall()
        if telemetry is not None:
            telemetry.close()

    if preempted:
        # The boundary checkpoint is already on disk (save_boundary waited);
        # skip the final-weights export — the grace period is for getting
        # out, and the relaunch resumes exactly where we stopped.
        metrics_logger.close()
        manager.close()
        return state

    if int(state.step) not in saved_steps:
        save_ckpt(int(state.step), state, wait=True)
    final = os.path.join(ckpt_dir, f"{cfg.name}-final")
    save_weights(final, state.variables)
    logger.info("Saved final weights to %s", final)
    metrics_logger.close()
    manager.close()
    return state


def main(argv=None) -> int:
    setup_logging()
    p = argparse.ArgumentParser(description=__doc__)
    add_train_args(p)
    add_model_args(p)
    args = p.parse_args(argv)
    plan = FaultPlan.parse(args.faults) if args.faults else None
    train(model_config_from_args(args), train_config_from_args(args),
          num_workers=args.num_workers, no_validation=args.no_validation,
          dataset_root=args.dataset_root, profile_steps=args.profile_steps,
          fault_plan=plan, metrics_port=args.metrics_port,
          metrics_host=args.metrics_host, workload=args.workload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
