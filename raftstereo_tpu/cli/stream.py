"""Offline streaming/video-stereo runner (docs/streaming.md).

Replays a temporally coherent synthetic moving-camera sequence through the
temporal warm-start subsystem (stream/) twice — once as a warm-started
session on the adaptive iteration ladder, once as the cold-start
full-iteration baseline — and reports per-frame EPE, temporal-consistency
EPE, and the iterations/latency the warm start saved:

    python -m raftstereo_tpu.cli.stream --frames 8 --image_size 64x96 \
        --stream_ladder 32 16 8 --restore_ckpt models/sceneflow.pth

Both passes run through the SAME serve-layer engine path
(``BatchEngine.infer_stream_batch``) the HTTP session endpoint uses, under
the same pad-and-bucket shape policy — with matching ``--divis_by``/
``--bucket_multiple``/``--max_batch_size`` the disparities here are
bitwise-identical to a session driven through ``cli.serve`` (tested in
tests/test_stream.py).  Prints one JSON object: a summary plus the two
per-frame record lists.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from ..config import (_parse_bucket, add_model_args, add_stream_args,
                      model_config_from_args, stream_config_from_args)
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights (default: random weights — "
                        "the warm-vs-cold comparison is still meaningful, "
                        "both passes share them)")
    g = p.add_argument_group("sequence")
    g.add_argument("--frames", type=int, default=8,
                   help="synthetic sequence length")
    g.add_argument("--image_size", type=_parse_bucket, default=(64, 96),
                   metavar="HxW", help="frame shape")
    g.add_argument("--start_disp", type=float, default=4.0,
                   help="frame-0 scene disparity in px")
    g.add_argument("--drift", type=float, default=0.5,
                   help="disparity drift per frame in px (scene depth "
                        "change)")
    g.add_argument("--pan", type=int, default=2,
                   help="camera pan per frame in px")
    g.add_argument("--seed", type=int, default=0)
    g = p.add_argument_group("engine (serve-parity shape policy)")
    g.add_argument("--divis_by", type=int, default=32)
    g.add_argument("--bucket_multiple", type=int, default=64)
    g.add_argument("--max_batch_size", type=int, default=1,
                   help="batch-axis padding; match the server's value for "
                        "bitwise serve parity (XLA numerics are only "
                        "identical at identical program shapes)")
    p.add_argument("--trace_out", default=None, metavar="PATH",
                   help="write the run's warp/forward spans as Chrome "
                        "trace-event JSON (open at ui.perfetto.dev; "
                        "docs/observability.md)")
    add_stream_args(p)
    add_model_args(p)
    return p


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)

    import jax

    from ..data.synthetic import StereoVideoSequence
    from ..models import RAFTStereo
    from ..stream import build_stream_engine, compare_warm_cold

    config = model_config_from_args(args)
    stream_cfg = stream_config_from_args(args)
    model = RAFTStereo(config)
    if args.restore_ckpt:
        variables = load_variables(args.restore_ckpt, config, model)
        logger.info("Loaded checkpoint %s", args.restore_ckpt)
    else:
        variables = model.init(jax.random.key(0))
        logger.warning("No --restore_ckpt: streaming RANDOM weights")

    seq = StereoVideoSequence(n_frames=args.frames, hw=args.image_size,
                              d0=args.start_disp, drift=args.drift,
                              pan=args.pan, seed=args.seed)
    engine = build_stream_engine(model, variables, args.image_size,
                                 stream_cfg,
                                 max_batch_size=args.max_batch_size,
                                 divis_by=args.divis_by,
                                 bucket_multiple=args.bucket_multiple)
    tracer = None
    if args.trace_out:
        from ..obs import Tracer
        tracer = Tracer(capacity=max(16 * args.frames, 1024))
    report = compare_warm_cold(engine, seq.frames, stream_cfg,
                               tracer=tracer)
    if tracer is not None:
        with open(args.trace_out, "w") as f:
            f.write(tracer.export_json())
        logger.info("wrote %d spans to %s (open at ui.perfetto.dev)",
                    len(tracer.spans()), args.trace_out)
    print(json.dumps({"summary": report["summary"],
                      "warm": report["warm"], "cold": report["cold"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
