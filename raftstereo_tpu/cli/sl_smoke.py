"""Structured-light pipeline smoke CLI (the working form of the reference's
``test.py`` dataset check, reference: test.py:9-46 — which as shipped indexes
an empty dataset, SURVEY.md §2.5).

    python -m raftstereo_tpu.cli.sl_smoke --root datasets/SL --scale 0.5

Loads the SL dataset, prints its size, and round-trips one sample through
the loader to prove shapes/dtypes.
"""

from __future__ import annotations

import argparse
import logging
import sys

from ..data.sl import StructuredLightDataset
from .common import setup_logging

logger = logging.getLogger(__name__)


def main(argv=None) -> int:
    setup_logging()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", required=True, help="SL dataset root")
    p.add_argument("--split", default="training")
    p.add_argument("--scale", type=float, default=0.5)
    p.add_argument("--index", type=int, default=0)
    args = p.parse_args(argv)

    ds = StructuredLightDataset(args.root, split=args.split, scale=args.scale)
    logger.info("SL dataset: %d samples", len(ds))
    if len(ds) == 0:
        logger.error("Dataset is empty — check --root layout "
                     "(see raftstereo_tpu/data/sl.py docstring)")
        return 1
    sample = ds[args.index]
    names = ("img_left", "img_right", "mask18", "disparity", "depth_mask")
    for name, v in zip(names, sample):
        logger.info("  %s: %s %s", name, v.shape, v.dtype)
    return 0


if __name__ == "__main__":
    sys.exit(main())
