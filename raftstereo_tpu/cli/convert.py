"""Checkpoint converter: reference ``.pth`` -> native Orbax weights.

    python -m raftstereo_tpu.cli.convert models/raftstereo-eth3d.pth \
        converted/raftstereo-eth3d [--corr_implementation reg ...]

``evaluate``/``demo``/``train --restore_ckpt`` already convert ``.pth``
on the fly (cli/common.py); this CLI persists the conversion so repeated
runs skip the torch load, and prints a parameter-count summary as a sanity
check (the reference prints the same count at eval time,
reference: evaluate_stereo.py:15-16,225).
"""

from __future__ import annotations

import argparse
import logging
import sys

from ..config import add_model_args, model_config_from_args
from ..models.raft_stereo import count_parameters
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def main(argv=None) -> int:
    setup_logging()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("src", help="reference .pth checkpoint (or Orbax dir)")
    p.add_argument("dst", help="output Orbax weights directory")
    add_model_args(p)
    args = p.parse_args(argv)
    config = model_config_from_args(args)

    variables = load_variables(args.src, config)
    from ..train.checkpoint import save_weights
    save_weights(args.dst, variables)
    logger.info("Converted %s -> %s (%.2fM parameters)", args.src, args.dst,
                count_parameters(variables) / 1e6)
    return 0


if __name__ == "__main__":
    sys.exit(main())
