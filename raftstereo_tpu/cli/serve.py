"""Serving entry point: dynamic-batching stereo inference over HTTP.

Serve (blocks until Ctrl-C):

    python -m raftstereo_tpu.cli.serve --restore_ckpt models/sceneflow.pth \
        --port 8080 --buckets 540x960 --max_batch_size 8

Load-generate against a running server (synthetic traffic):

    python -m raftstereo_tpu.cli.serve --loadgen --port 8080 \
        --requests 64 --concurrency 4 --image_size 540x960

Endpoints, wire format and the metrics reference live in docs/serving.md.
All model flags (``add_model_args``) and serving knobs (``add_serve_args``)
come from the shared typed configs in config.py — no fresh argparse block.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

import numpy as np

from ..config import (_parse_bucket, add_cluster_args, add_model_args,
                      add_sched_args, add_serve_args, add_stream_args,
                      cluster_config_from_args, model_config_from_args,
                      sched_config_from_args, serve_config_from_args,
                      stream_config_from_args)
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights to serve")
    p.add_argument("--loadgen", action="store_true",
                   help="run the load generator against --host/--port "
                        "instead of serving")
    g = p.add_argument_group("loadgen")
    g.add_argument("--requests", type=int, default=64)
    g.add_argument("--concurrency", type=int, default=4)
    g.add_argument("--open_rate", type=float, default=None,
                   help="open-loop arrival rate in requests/sec "
                        "(default: closed loop)")
    g.add_argument("--image_size", type=_parse_bucket, default=(540, 960),
                   metavar="HxW", help="synthetic request image shape")
    g.add_argument("--request_iters", type=int, default=None,
                   help="explicit per-request GRU iterations; must be one "
                        "of the server's configured levels (--serve_iters "
                        "or --degraded_iters). default: server-adaptive")
    g.add_argument("--sequence_len", type=int, default=None,
                   help="sequence-replay load: frames per synthetic video "
                        "session, sent with session_id/seq_no so the "
                        "server warm-starts them (docs/streaming.md)")
    g.add_argument("--accuracy", default=None,
                   choices=["certified", "fast", "turbo"],
                   help="accuracy tier sent with every load-gen request "
                        "(the server must advertise it; docs/serving.md "
                        "\"Accuracy tiers\")")
    g.add_argument("--json", action="store_true", dest="wire_json",
                   help="send the legacy base64 JSON /predict dialect "
                        "instead of the default binary wire frames "
                        "(docs/wire_format.md)")
    g.add_argument("--response_encoding", default="f32",
                   choices=["f32", "int16"],
                   help="binary-dialect disparity encoding: bitwise "
                        "float32 (default) or int16 fixed-point with a "
                        "per-response exactness manifest")
    p.add_argument("--no_stream", action="store_true",
                   help="disable the session-aware streaming path "
                        "(session_id/seq_no on /predict)")
    p.add_argument("--stream_warmup", action="store_true",
                   help="compile every (bucket, stream-ladder level) at "
                        "startup so mid-stream level switches never pay "
                        "an XLA compile")
    p.add_argument("--sched", action="store_true",
                   help="iteration-level continuous batching: requests "
                        "join/leave one running batch per bucket at "
                        "iteration boundaries (per-request deadline_ms/"
                        "priority on /predict, no head-of-line blocking; "
                        "docs/serving.md)")
    p.add_argument("--warmup_async", action="store_true",
                   help="serve /healthz immediately (live) and warm in "
                        "the background; ready flips true when warmup "
                        "finishes — what a router-fronted restart wants "
                        "(docs/serving.md \"Cluster\")")
    add_serve_args(p)
    add_sched_args(p)
    add_stream_args(p)
    add_cluster_args(p)
    add_model_args(p)
    return p


def run_loadgen(args) -> int:
    from ..serve import run_load, synthetic_pair_pool

    h, w = args.image_size
    stats = run_load(
        args.host, args.port,
        synthetic_pair_pool(h, w, n=min(8, args.requests)),
        requests=args.requests, concurrency=args.concurrency,
        mode="open" if args.open_rate else "closed", rate=args.open_rate,
        iters=args.request_iters, sequence_len=args.sequence_len,
        accuracy=args.accuracy,
        wire_format="json" if args.wire_json else "binary",
        response_encoding=args.response_encoding)
    print(json.dumps(stats))
    return 0


def main(argv=None) -> int:
    setup_logging()
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.loadgen:
        if args.sequence_len is not None and args.request_iters is not None:
            parser.error("--sequence_len cannot be combined with "
                         "--request_iters: the server's adaptive "
                         "controller owns per-frame iterations for "
                         "session traffic")
        return run_loadgen(args)

    import jax

    from ..models import RAFTStereo
    from ..serve import build_server

    config = model_config_from_args(args)
    stream_cfg = None if args.no_stream else stream_config_from_args(args)
    sched_cfg = sched_config_from_args(args) if args.sched else None
    cluster_cfg = cluster_config_from_args(args)
    serve_cfg = serve_config_from_args(args, stream=stream_cfg,
                                       stream_warmup=args.stream_warmup,
                                       sched=sched_cfg,
                                       cluster=cluster_cfg)
    model = RAFTStereo(config)
    if args.restore_ckpt:
        variables = load_variables(args.restore_ckpt, config, model)
        logger.info("Loaded checkpoint %s", args.restore_ckpt)
    else:
        variables = model.init(jax.random.key(0))
        logger.warning("No --restore_ckpt: serving RANDOM weights")

    server = build_server(model, variables, serve_cfg,
                          warmup_async=args.warmup_async)
    print(json.dumps({"serving": f"http://{serve_cfg.host}:{server.port}",
                      "endpoints": ["/predict", "/metrics", "/healthz",
                                    "/debug/trace", "/debug/profile",
                                    "/debug/threads", "/debug/vars"]}),
          flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
