"""Certify serving accuracy tiers: measure per-tier EPE deltas vs fp32.

    python -m raftstereo_tpu.cli.certify --restore_ckpt models/sf.pth \
        --tiers fast turbo --out certification.json

Runs the certification harness (eval/certify.py) on synthetic stereo
pairs with exact ground truth and writes the certification manifest the
server validates at startup (``cli.serve --tiers ... --cert_manifest``)
before advertising a tier on ``/predict``.  Exits non-zero when any
requested tier measures over its bound — wire it as the CI gate between
"quantized kernels changed" and "tier deployed".

The ``cascade`` verb certifies speculative tier-cascade schedules
(serve/cascade/, docs/serving.md "Tier cascade") the same way — masked
EPE delta vs the fp32 monolithic reference at equal total iterations —
and can merge the results into an existing tier manifest:

    python -m raftstereo_tpu.cli.certify cascade \
        --restore_ckpt models/sf.pth --schedules int8:24+fp32:8 \
        --base certification.json --out certification.json
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from ..config import add_model_args, model_config_from_args
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def _parse_bound(text: str):
    try:
        tier, px = text.split("=")
        bound = float(px)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bound {text!r} is not TIER=PX (e.g. fast=0.5)")
    if tier not in ("fast", "turbo"):
        # A typo here must not silently fall back to the loose default
        # bound — the override would be ignored and the tier certified
        # against a 5x weaker gate than the operator asked for.
        raise argparse.ArgumentTypeError(
            f"bound tier {tier!r} is not certifiable (fast/turbo)")
    return tier, bound


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights to certify (default: "
                        "random weights — smoke/dev only)")
    p.add_argument("--tiers", nargs="+", default=["fast", "turbo"],
                   choices=["fast", "turbo"], metavar="TIER",
                   help="tiers to measure ('certified' is the fp32 "
                        "reference itself and needs no certificate)")
    p.add_argument("--out", default="certification.json",
                   help="manifest path the server's --cert_manifest reads")
    p.add_argument("--cert_height", type=int, default=256)
    p.add_argument("--cert_width", type=int, default=320)
    p.add_argument("--cert_pairs", type=int, default=4,
                   help="synthetic pairs in the certification set")
    p.add_argument("--cert_iters", type=int, default=16,
                   help="GRU iterations per certification forward")
    p.add_argument("--cert_seed", type=int, default=0)
    p.add_argument("--bound", type=_parse_bound, nargs="+", default=[],
                   metavar="TIER=PX",
                   help="override a tier's mean-EPE-delta bound in px "
                        "(defaults: eval/certify.DEFAULT_BOUNDS)")
    add_model_args(p)
    return p


def _parse_cascade_bound(text: str):
    try:
        schedule, px = text.rsplit("=", 1)
        return schedule, float(px)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bound {text!r} is not SCHEDULE=PX "
            "(e.g. int8:24+fp32:8=0.5)")


def build_cascade_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raftstereo_tpu.cli.certify cascade",
        description="Certify speculative tier-cascade schedules: masked "
                    "EPE delta vs the fp32 monolithic reference at equal "
                    "total iterations (docs/serving.md \"Tier cascade\")")
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights to certify (default: "
                        "random weights — smoke/dev only)")
    p.add_argument("--schedules", nargs="+", required=True,
                   metavar="SCHEDULE",
                   help="cascade schedules to measure, e.g. "
                        "int8:24+fp32:8 (the iteration budget is the "
                        "schedule's — there is no --cert_iters)")
    p.add_argument("--out", default="certification.json",
                   help="manifest path the server's --cert_manifest reads")
    p.add_argument("--base", default=None,
                   help="existing manifest to merge the cascades table "
                        "into (same architecture + platform required); "
                        "omit to write a standalone cascade manifest")
    p.add_argument("--cert_height", type=int, default=256)
    p.add_argument("--cert_width", type=int, default=320)
    p.add_argument("--cert_pairs", type=int, default=4,
                   help="synthetic pairs in the certification set")
    p.add_argument("--cert_seed", type=int, default=0)
    p.add_argument("--cascade_bound", type=_parse_cascade_bound,
                   nargs="+", default=[], metavar="SCHEDULE=PX",
                   help="override a schedule's mean-EPE-delta bound in px "
                        "(default: eval/certify.DEFAULT_CASCADE_BOUND)")
    add_model_args(p)
    return p


def _cascade_main(argv) -> int:
    args = build_cascade_parser().parse_args(argv)
    config = model_config_from_args(args)

    import jax

    from ..eval.certify import (certify_cascades, load_manifest,
                                write_manifest)
    from ..models import RAFTStereo
    from ..serve.cascade.schedule import parse_schedule

    # Parse up front so a grammar typo fails before any model work.
    canon = [parse_schedule(s).schedule for s in args.schedules]
    model = RAFTStereo(config)
    if args.restore_ckpt:
        variables = load_variables(args.restore_ckpt, config, model)
        logger.info("Loaded checkpoint %s", args.restore_ckpt)
    else:
        variables = model.init(jax.random.key(0),
                               (args.cert_height, args.cert_width))
        logger.warning("No --restore_ckpt: certifying RANDOM weights "
                       "(smoke/dev only — the manifest fingerprints the "
                       "architecture, not the weights)")
    base = load_manifest(args.base) if args.base else None
    bounds = {parse_schedule(s).schedule: px
              for s, px in args.cascade_bound}
    manifest = certify_cascades(
        config, variables, canon,
        hw=(args.cert_height, args.cert_width), n_pairs=args.cert_pairs,
        seed=args.cert_seed, bounds=bounds or None, base=base)
    write_manifest(manifest, args.out)
    summary = {s: {k: e[k] for k in ("epe_delta", "bound", "certified")}
               for s, e in manifest["cascades"].items()}
    print(json.dumps({"manifest": args.out, "cascades": summary}))
    uncertified = [s for s in canon
                   if not manifest["cascades"][s]["certified"]]
    if uncertified:
        logger.error("cascades over bound: %s", uncertified)
        return 1
    return 0


def main(argv=None) -> int:
    setup_logging()
    if argv is None:
        argv = sys.argv[1:]
    # Verb-style dispatch rides in front of the historical flag-only
    # parser, so every existing invocation is byte-compatible.
    if list(argv[:1]) == ["cascade"]:
        return _cascade_main(list(argv[1:]))
    args = build_parser().parse_args(argv)
    config = model_config_from_args(args)

    import jax

    from ..eval.certify import certify_tiers, write_manifest
    from ..models import RAFTStereo

    model = RAFTStereo(config)
    if args.restore_ckpt:
        variables = load_variables(args.restore_ckpt, config, model)
        logger.info("Loaded checkpoint %s", args.restore_ckpt)
    else:
        variables = model.init(jax.random.key(0),
                               (args.cert_height, args.cert_width))
        logger.warning("No --restore_ckpt: certifying RANDOM weights "
                       "(smoke/dev only — the manifest fingerprints the "
                       "architecture, not the weights)")

    manifest = certify_tiers(
        config, variables, tuple(args.tiers),
        hw=(args.cert_height, args.cert_width), n_pairs=args.cert_pairs,
        iters=args.cert_iters, seed=args.cert_seed,
        bounds=dict(args.bound) or None)
    write_manifest(manifest, args.out)
    summary = {tier: {k: e[k] for k in ("epe_delta", "bound", "certified")}
               for tier, e in manifest["tiers"].items()}
    print(json.dumps({"manifest": args.out, "tiers": summary}))
    uncertified = [t for t, e in manifest["tiers"].items()
                   if not e["certified"]]
    if uncertified:
        logger.error("tiers over bound: %s", uncertified)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
