"""Certify serving accuracy tiers: measure per-tier EPE deltas vs fp32.

    python -m raftstereo_tpu.cli.certify --restore_ckpt models/sf.pth \
        --tiers fast turbo --out certification.json

Runs the certification harness (eval/certify.py) on synthetic stereo
pairs with exact ground truth and writes the certification manifest the
server validates at startup (``cli.serve --tiers ... --cert_manifest``)
before advertising a tier on ``/predict``.  Exits non-zero when any
requested tier measures over its bound — wire it as the CI gate between
"quantized kernels changed" and "tier deployed".
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from ..config import add_model_args, model_config_from_args
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def _parse_bound(text: str):
    try:
        tier, px = text.split("=")
        bound = float(px)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bound {text!r} is not TIER=PX (e.g. fast=0.5)")
    if tier not in ("fast", "turbo"):
        # A typo here must not silently fall back to the loose default
        # bound — the override would be ignored and the tier certified
        # against a 5x weaker gate than the operator asked for.
        raise argparse.ArgumentTypeError(
            f"bound tier {tier!r} is not certifiable (fast/turbo)")
    return tier, bound


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights to certify (default: "
                        "random weights — smoke/dev only)")
    p.add_argument("--tiers", nargs="+", default=["fast", "turbo"],
                   choices=["fast", "turbo"], metavar="TIER",
                   help="tiers to measure ('certified' is the fp32 "
                        "reference itself and needs no certificate)")
    p.add_argument("--out", default="certification.json",
                   help="manifest path the server's --cert_manifest reads")
    p.add_argument("--cert_height", type=int, default=256)
    p.add_argument("--cert_width", type=int, default=320)
    p.add_argument("--cert_pairs", type=int, default=4,
                   help="synthetic pairs in the certification set")
    p.add_argument("--cert_iters", type=int, default=16,
                   help="GRU iterations per certification forward")
    p.add_argument("--cert_seed", type=int, default=0)
    p.add_argument("--bound", type=_parse_bound, nargs="+", default=[],
                   metavar="TIER=PX",
                   help="override a tier's mean-EPE-delta bound in px "
                        "(defaults: eval/certify.DEFAULT_BOUNDS)")
    add_model_args(p)
    return p


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)
    config = model_config_from_args(args)

    import jax

    from ..eval.certify import certify_tiers, write_manifest
    from ..models import RAFTStereo

    model = RAFTStereo(config)
    if args.restore_ckpt:
        variables = load_variables(args.restore_ckpt, config, model)
        logger.info("Loaded checkpoint %s", args.restore_ckpt)
    else:
        variables = model.init(jax.random.key(0),
                               (args.cert_height, args.cert_width))
        logger.warning("No --restore_ckpt: certifying RANDOM weights "
                       "(smoke/dev only — the manifest fingerprints the "
                       "architecture, not the weights)")

    manifest = certify_tiers(
        config, variables, tuple(args.tiers),
        hw=(args.cert_height, args.cert_width), n_pairs=args.cert_pairs,
        iters=args.cert_iters, seed=args.cert_seed,
        bounds=dict(args.bound) or None)
    write_manifest(manifest, args.out)
    summary = {tier: {k: e[k] for k in ("epe_delta", "bound", "certified")}
               for tier, e in manifest["tiers"].items()}
    print(json.dumps({"manifest": args.out, "tiers": summary}))
    uncertified = [t for t, e in manifest["tiers"].items()
                   if not e["certified"]]
    if uncertified:
        logger.error("tiers over bound: %s", uncertified)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
