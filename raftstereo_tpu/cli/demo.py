"""Demo entry point: infer disparity for image pairs and save visualisations
(reference: demo.py).

    python -m raftstereo_tpu.cli.demo --restore_ckpt models/raftstereo-eth3d.pth \
        -l "datasets/ETH3D/two_view_training/*/im0.png" \
        -r "datasets/ETH3D/two_view_training/*/im1.png" \
        --output_directory demo_output --save_numpy

Outputs jet-colormapped PNGs of POSITIVE disparity (the model predicts
negative x-flow; the reference negates before saving, demo.py:48-49) and
optionally raw ``.npy`` fields.
"""

from __future__ import annotations

import argparse
import glob
import logging
import os
import sys

import numpy as np
from PIL import Image

from ..config import add_model_args, model_config_from_args
from ..eval import Evaluator
from ..models import RAFTStereo
from ..utils.viz import save_disparity_png
from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def load_image(path: str) -> np.ndarray:
    img = np.asarray(Image.open(path), np.uint8)
    if img.ndim == 2:
        img = np.tile(img[..., None], (1, 1, 3))
    return img[..., :3].astype(np.float32)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--restore_ckpt", required=True,
                   help=".pth or Orbax weights")
    p.add_argument("-l", "--left_imgs", required=True,
                   help="glob for left (reference) images")
    p.add_argument("-r", "--right_imgs", required=True,
                   help="glob for right images")
    p.add_argument("--output_directory", default="demo_output")
    p.add_argument("--save_numpy", action="store_true",
                   help="also save raw disparity as .npy")
    p.add_argument("--valid_iters", type=int, default=32)
    p.add_argument("--tiled", action="store_true",
                   help="tile very large images (4K+): fixed-shape tiles "
                        "streamed through HBM, feather-blended on host "
                        "(BASELINE.json config #5; use with "
                        "--corr_implementation alt)")
    p.add_argument("--tile_size", type=int, nargs=2, default=(1056, 1568),
                   metavar=("H", "W"), help="tile shape for --tiled")
    p.add_argument("--tile_overlap", type=int, default=128)
    p.add_argument("--max_disparity", type=int, default=512,
                   help="--tiled only: untrusted left strip width per tile")
    add_model_args(p)
    return p


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)
    config = model_config_from_args(args)

    model = RAFTStereo(config)
    variables = load_variables(args.restore_ckpt, config, model)
    run = Evaluator(model, variables, iters=args.valid_iters)

    left = sorted(glob.glob(args.left_imgs, recursive=True))
    right = sorted(glob.glob(args.right_imgs, recursive=True))
    if not left or len(left) != len(right):
        logger.error("Bad globs: %d left vs %d right images",
                     len(left), len(right))
        return 1
    logger.info("Found %d image pairs. Saving files to %s/",
                len(left), args.output_directory)
    os.makedirs(args.output_directory, exist_ok=True)

    # Output stems: basenames when unique; otherwise the parent directory
    # (datasets like ETH3D name every left image im0.png — the reference
    # uses the scene directory for this reason, demo.py:44); index as a
    # last resort so pairs never overwrite each other.
    stems = [os.path.splitext(os.path.basename(p))[0] for p in left]
    if len(set(stems)) != len(stems):
        stems = [os.path.basename(os.path.dirname(p)) for p in left]
    if len(set(stems)) != len(stems):
        stems = [f"{i:06d}_{s}" for i, s in enumerate(stems)]

    tiled_fn = None
    if args.tiled:
        from ..eval.tiled import tiled_infer
        tiled_fn = model.jitted_infer(iters=args.valid_iters)

    for imfile1, imfile2, stem in zip(left, right, stems):
        if args.tiled:
            flow = tiled_infer(
                model, variables, load_image(imfile1), load_image(imfile2),
                iters=args.valid_iters, tile_hw=tuple(args.tile_size),
                overlap=args.tile_overlap, disp_margin=args.max_disparity,
                infer_fn=tiled_fn)
        else:
            flow = run(load_image(imfile1), load_image(imfile2))
        disparity = -flow  # positive disparity for output (reference: demo.py:48)
        out = os.path.join(args.output_directory, stem)
        if args.save_numpy:
            np.save(f"{out}.npy", disparity)
        save_disparity_png(f"{out}.png", disparity)
        if args.tiled:
            logger.info("%s -> %s.png (tiled)", imfile1, out)
        else:
            logger.info("%s -> %s.png (%.3fs)", imfile1, out, run.last_runtime)
    return 0


if __name__ == "__main__":
    sys.exit(main())
