"""Trace-driven SLO harness: generate, replay, fit, what-if.

The four verbs of docs/slo_harness.md, end to end against a live
server or ``cli.router`` cluster:

    # 1. a seeded burst trace with sessions + tiers + deadlines
    python -m raftstereo_tpu.cli.loadgen gen --out trace.jsonl \
        --requests 256 --shape burst --session_fraction 0.3 \
        --tiers default:3 fast:1 --priorities high:1 normal:3 \
        --deadline high:2000

    # 2. open-loop replay on the trace's schedule; SLO verdict + rows
    python -m raftstereo_tpu.cli.loadgen replay --trace trace.jsonl \
        --port 8000 --report slo_report.json --p99_ms 5000

    # 3. fit requests/s/chip from the replay's rows
    python -m raftstereo_tpu.cli.loadgen fit --report slo_report.json \
        --chips 2 --out capacity.json

    # 4. "N chips serve M users at SLO"
    python -m raftstereo_tpu.cli.loadgen whatif --model capacity.json \
        --chips 8 --rps_per_user 0.2

The fitted model feeds serving directly: ``cli.serve
--capacity_model capacity.json --target_rps 50`` (or the same flags on
``cli.router``) turns autoscale advice into a recommended replica
count and the ``cluster_capacity_headroom`` gauge.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import math
import sys

from .common import setup_logging

logger = logging.getLogger(__name__)


def _parse_hw(text: str):
    try:
        h, w = text.lower().split("x")
        return int(h), int(w)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"{text!r} is not HxW (e.g. 540x960)")


def _parse_weight(text: str):
    if "=" in text:
        # The unambiguous form — required for cascade tiers, whose
        # schedule grammar owns the colons (cascade:int8:24+fp32:8=2).
        name, _, weight = text.rpartition("=")
    elif text.startswith("cascade:"):
        return text, 1.0
    else:
        name, _, weight = text.partition(":")
    try:
        return name, float(weight or 1.0)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"{text!r} is not NAME[:WEIGHT] or NAME=WEIGHT "
            f"(e.g. fast:2, cascade:int8:24+fp32:8=2)")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m raftstereo_tpu.cli.loadgen", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="verb", required=True)

    g = sub.add_parser("gen", help="generate a seeded synthetic trace")
    g.add_argument("--out", required=True, help="trace JSONL path")
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--requests", type=int, default=64)
    g.add_argument("--duration_s", type=float, default=4.0)
    g.add_argument("--shape", choices=("poisson", "burst", "diurnal"),
                   default="burst")
    g.add_argument("--burst_factor", type=float, default=4.0)
    g.add_argument("--burst_fraction", type=float, default=0.25)
    g.add_argument("--resolutions", nargs="+", type=_parse_hw,
                   default=[(540, 960)], metavar="HxW")
    g.add_argument("--session_fraction", type=float, default=0.0,
                   help="fraction of events that are stream frames")
    g.add_argument("--sequence_len", type=int, default=4,
                   help="frames per synthetic session")
    g.add_argument("--tiers", nargs="+", type=_parse_weight,
                   default=[("default", 1.0)], metavar="TIER[:W]",
                   help="accuracy-tier mix (default/certified/fast/turbo, "
                        "or cascade:<schedule> for speculative tier "
                        "cascades — weight via =W there, e.g. "
                        "cascade:int8:24+fp32:8=2)")
    g.add_argument("--priorities", nargs="+", type=_parse_weight,
                   default=[("normal", 1.0)], metavar="PRIO[:W]")
    g.add_argument("--deadline", nargs="+", type=_parse_weight,
                   default=[], metavar="PRIO:MS",
                   help="deadline_ms attached to events of a priority")
    g.add_argument("--iters", nargs="+", type=int, default=[],
                   help="explicit iteration targets to mix in")
    g.add_argument("--iters_fraction", type=float, default=0.5)

    r = sub.add_parser("replay", help="open-loop replay against a live "
                                      "server/router; writes the SLO "
                                      "report")
    r.add_argument("--trace", required=True)
    r.add_argument("--host", default="127.0.0.1")
    r.add_argument("--port", type=int, required=True)
    r.add_argument("--concurrency", type=int, default=4)
    r.add_argument("--timeout_s", type=float, default=120.0)
    r.add_argument("--retries", type=int, default=0)
    r.add_argument("--pair_seed", type=int, default=0)
    r.add_argument("--speed", type=float, default=1.0,
                   help=">1 replays the trace faster than recorded")
    r.add_argument("--wire", default="binary",
                   choices=["binary", "json"],
                   help="/predict dialect: binary wire frames (default) "
                        "or the legacy base64 JSON — replay the same "
                        "trace under both to measure the wire-bytes/pair "
                        "reduction (docs/wire_format.md)")
    r.add_argument("--response_encoding", default="f32",
                   choices=["f32", "int16"],
                   help="binary-dialect disparity encoding (int16 adds "
                        "the per-response exactness manifest)")
    r.add_argument("--report", default=None,
                   help="write verdict + per-request rows JSON here")
    r.add_argument("--chaos", default=None, metavar="PLAN.json",
                   help="chaos plan (loadgen/chaos.py) armed against "
                        "trace time during the replay; its declared "
                        "degraded windows become SLO windows "
                        "(docs/fault_tolerance.md)")
    r.add_argument("--chaos_target", action="append", default=[],
                   metavar="NAME=HOST:PORT",
                   help="map a chaos plan's logical target name to an "
                        "endpoint (repeatable); the replayed endpoint "
                        "itself is always available as 'default'")
    r.add_argument("--p50_ms", type=float, default=math.inf,
                   help="SLO: p50 latency bound over all requests")
    r.add_argument("--p99_ms", type=float, default=math.inf)
    r.add_argument("--max_shed_rate", type=float, default=1.0)
    r.add_argument("--min_deadline_hit_rate", type=float, default=0.0)

    f = sub.add_parser("fit", help="fit the capacity model from a "
                                   "replay report")
    f.add_argument("--report", required=True,
                   help="replay report JSON (needs its rows)")
    f.add_argument("--chips", type=int, required=True,
                   help="chips/replicas the replayed endpoint ran on")
    f.add_argument("--out", required=True, help="capacity model JSON path")

    w = sub.add_parser("whatif", help="answer 'N chips serve M users' "
                                      "from a fitted model")
    w.add_argument("--model", required=True)
    w.add_argument("--chips", type=int, default=None)
    w.add_argument("--target_rps", type=float, default=None)
    w.add_argument("--rps_per_user", type=float, default=1.0)
    w.add_argument("--headroom", type=float, default=0.1)
    return p


def _cmd_gen(args) -> int:
    from ..loadgen import trace as T

    spec = T.TraceSpec(
        seed=args.seed, requests=args.requests,
        duration_s=args.duration_s, shape=args.shape,
        burst_factor=args.burst_factor,
        burst_fraction=args.burst_fraction,
        resolutions=tuple(tuple(r) for r in args.resolutions),
        session_fraction=args.session_fraction,
        sequence_len=args.sequence_len,
        tier_mix=tuple(args.tiers),
        priority_mix=tuple(args.priorities),
        deadlines=tuple(args.deadline),
        iters_choices=tuple(args.iters),
        iters_fraction=args.iters_fraction)
    events = T.generate(spec)
    T.write_trace(args.out, events, header=spec.header())
    print(json.dumps({"trace": args.out, "events": len(events),
                      "seed": spec.seed, "shape": spec.shape,
                      "duration_s": spec.duration_s}), flush=True)
    return 0


def _cmd_replay(args) -> int:
    import time

    from ..loadgen import replay as R
    from ..loadgen import slo as S
    from ..loadgen import trace as T
    from ..serve.client import ServeClient

    header, events = T.read_trace(args.trace)
    cfg = R.ReplayConfig(host=args.host, port=args.port,
                         concurrency=args.concurrency,
                         timeout_s=args.timeout_s, retries=args.retries,
                         pair_seed=args.pair_seed, speed=args.speed,
                         wire_format=args.wire,
                         response_encoding=args.response_encoding)
    chaos_plan = controller = None
    windows = ()
    if args.chaos:
        from ..loadgen import chaos as X

        chaos_plan = X.ChaosPlan.load(args.chaos)
        targets = {"default": (args.host, args.port)}
        for item in args.chaos_target:
            name, _, hp = item.partition("=")
            host, _, port = hp.rpartition(":")
            if not (name and host and port):
                raise SystemExit(
                    f"--chaos_target {item!r} is not NAME=HOST:PORT")
            targets[name] = (host, int(port))
        controller = X.ChaosController(chaos_plan, targets,
                                       timeout_s=args.timeout_s)
        windows = chaos_plan.degraded_windows()
    scraper = ServeClient(args.host, args.port, timeout=args.timeout_s)
    try:
        before = scraper.metrics_text()
        t0 = time.perf_counter()
        recorder = R.replay(events, cfg, chaos=controller)
        wall_s = time.perf_counter() - t0
        after = scraper.metrics_text()
    finally:
        scraper.close()
    spec = S.SLOSpec(classes=(S.SLOClass(
        p50_ms=args.p50_ms, p99_ms=args.p99_ms,
        max_shed_rate=args.max_shed_rate,
        min_deadline_hit_rate=args.min_deadline_hit_rate),),
        windows=windows)
    rows = recorder.rows()
    verdict = S.evaluate(spec, rows, wall_s=wall_s,
                         metrics_before=before, metrics_after=after)
    chaos_summary = controller.summary() if controller is not None else None
    if args.report:
        report = {"trace": header, "verdict": verdict,
                  "rows": [dataclasses.asdict(r) for r in rows]}
        if chaos_summary is not None:
            report["chaos"] = chaos_summary
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    out = {k: verdict[k] for k in
           ("pass", "requests", "wall_s", "groups")}
    if "wire" in verdict:
        out["wire"] = verdict["wire"]
    if chaos_summary is not None:
        out["chaos"] = {k: chaos_summary[k]
                        for k in ("actions", "armed", "failed")}
    out["report"] = args.report
    print(json.dumps(out), flush=True)
    return 0 if verdict["pass"] else 1


def _cmd_fit(args) -> int:
    from ..loadgen import capacity as C
    from ..loadgen.records import RequestRow

    with open(args.report) as f:
        report = json.load(f)
    rows = [RequestRow(**d) for d in report["rows"]]
    model = C.fit(rows, chips=args.chips,
                  wall_s=report["verdict"]["wall_s"])
    C.save_model(model, args.out)
    print(json.dumps({"model": args.out,
                      "per_chip_rps": model["per_chip_rps"],
                      "utilization": model["utilization"],
                      "buckets": len(model["buckets"])}), flush=True)
    return 0


def _cmd_whatif(args) -> int:
    from ..loadgen import capacity as C

    model = C.load_model(args.model)
    answer = C.whatif(model, chips=args.chips,
                      target_rps=args.target_rps,
                      rps_per_user=args.rps_per_user,
                      headroom=args.headroom)
    print(json.dumps(answer), flush=True)
    return 0


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)
    return {"gen": _cmd_gen, "replay": _cmd_replay,
            "fit": _cmd_fit, "whatif": _cmd_whatif}[args.verb](args)


if __name__ == "__main__":
    sys.exit(main())
