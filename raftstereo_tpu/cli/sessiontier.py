"""Durable session tier: shared external store for warm-start state.

Start the tier, then point backends (write-behind pushes) and the
router (lost-home warm resume) at it:

    python -m raftstereo_tpu.cli.sessiontier --port 8082 &
    python -m raftstereo_tpu.cli.serve --port 8080 \
        --stream --session_tier 127.0.0.1:8082 ... &
    python -m raftstereo_tpu.cli.router --port 8000 \
        --backends 127.0.0.1:8080 127.0.0.1:8090 \
        --session_tier 127.0.0.1:8082

Backends push each session's latest snapshot AFTER the frame is
answered (write-behind — the tier is never on a request path); when a
session's home backend is lost, the router resumes it WARM on a
survivor from the tier's latest snapshot instead of the cold_lost
fallback.  A tier outage degrades cleanly to backend-local sessions —
counted, never an error.  Semantics: docs/streaming.md "Durable
sessions"; chaos grammar (``tier_outage``/``tier_slow``):
docs/fault_tolerance.md.

Like the router, the tier is model-free: it never imports the
engine/model stack, stores snapshots as the verbatim wire JSON the
backends exchange, and starts in milliseconds.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from ..config import add_tier_args, tier_config_from_args
from .common import setup_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    add_tier_args(p)
    return p


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)
    cfg = tier_config_from_args(args)

    from ..stream.tier import build_session_tier

    tier = build_session_tier(cfg)
    print(json.dumps({
        "tier": f"http://{cfg.host}:{tier.port}",
        "session_limit": cfg.session_limit,
        "budget_mb": cfg.budget_mb,
        "endpoints": ["/healthz", "/metrics", "/debug/sessions",
                      "/debug/faults", "/debug/trace", "/debug/vars"],
    }), flush=True)
    try:
        tier.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        tier.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
