"""Fleet observatory client: operator verbs against a running router.

    python -m raftstereo_tpu.cli.obs trace  --router 127.0.0.1:8000 \
        --trace_id <id> [--out trace.json]
    python -m raftstereo_tpu.cli.obs fleet  --router 127.0.0.1:8000
    python -m raftstereo_tpu.cli.obs alerts --router 127.0.0.1:8000 \
        [--watch 5]

``trace`` fetches the STITCHED cross-hop tree for one trace id
(``GET /debug/trace?trace_id=`` — router + every backend + session
tier merged into one Perfetto-loadable document); ``--out`` writes the
chrome://tracing JSON, otherwise the span tree prints as an indented
summary.  ``fleet`` dumps the federated ``GET /metrics/fleet``
exposition verbatim.  ``alerts`` prints the live burn-rate evaluation
(``GET /debug/alerts``); ``--watch N`` re-evaluates every N seconds
until interrupted.  Semantics: docs/observability.md "Fleet
observatory".

Like the router it talks to, this client is model-free and
stdlib-only: it never imports the engine/model stack.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from urllib.parse import quote

from .common import setup_logging


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="verb", required=True)

    def _common(sp):
        sp.add_argument("--router", default="127.0.0.1:8000",
                        help="router host:port (default %(default)s)")
        sp.add_argument("--timeout_s", type=float, default=5.0,
                        help="per-request HTTP timeout")

    t = sub.add_parser("trace", help="fetch one stitched cross-hop trace")
    _common(t)
    t.add_argument("--trace_id", required=True,
                   help="trace id to stitch (the request's X-Request-Id "
                        "unless the client sent X-Trace-Context)")
    t.add_argument("--out", default=None,
                   help="write the chrome://tracing JSON here instead of "
                        "printing the span-tree summary")

    f = sub.add_parser("fleet", help="dump the federated /metrics/fleet "
                                     "exposition")
    _common(f)

    a = sub.add_parser("alerts", help="print the live burn-rate alert "
                                      "evaluation")
    _common(a)
    a.add_argument("--watch", type=float, default=None, metavar="SECONDS",
                   help="re-evaluate every SECONDS until interrupted")
    return p


def _get(router: str, path: str, timeout_s: float) -> bytes:
    with urllib.request.urlopen(f"http://{router}{path}",
                                timeout=timeout_s) as resp:
        return resp.read()


def _print_tree(node, depth=0):
    span = node["span"]
    dur_ms = span.get("dur_us", 0) / 1e3
    attrs = span.get("attrs") or {}
    extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    print(f"{'  ' * depth}{span.get('source', '?')}/{span['name']} "
          f"{dur_ms:.3f}ms{(' ' + extra) if extra else ''}")
    for child in node.get("children", ()):
        _print_tree(child, depth + 1)


def _alerts_line(doc) -> str:
    parts = []
    for cls in doc.get("classes", ()):
        parts.append(f"{cls['class']}: {cls['state_name']} "
                     f"burn={cls['burn']} (fast={cls['burn_fast']} "
                     f"slow={cls['burn_slow']})")
    return "; ".join(parts) or "no classes"


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)
    try:
        if args.verb == "trace":
            raw = _get(args.router, "/debug/trace?trace_id="
                       + quote(args.trace_id, safe=""), args.timeout_s)
            doc = json.loads(raw)
            if args.out:
                with open(args.out, "w") as fh:
                    json.dump(doc, fh)
                print(json.dumps({"out": args.out,
                                  "stitch": doc.get("stitch")}))
            else:
                stitch = doc.get("stitch", {})
                print(f"trace {args.trace_id}: "
                      f"{stitch.get('n_spans', 0)} spans from "
                      f"{', '.join(stitch.get('sources', ()))}"
                      + (f" (gaps: {', '.join(stitch['gaps'])})"
                         if stitch.get("gaps") else ""))
                for root in doc.get("tree", ()):
                    _print_tree(root)
        elif args.verb == "fleet":
            sys.stdout.write(
                _get(args.router, "/metrics/fleet",
                     args.timeout_s).decode("utf-8", "replace"))
        else:  # alerts
            while True:
                doc = json.loads(_get(args.router, "/debug/alerts",
                                      args.timeout_s))
                if args.watch is None:
                    print(json.dumps(doc, indent=2))
                    break
                print(_alerts_line(doc), flush=True)
                time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
