"""Structured-light workload CLI: dataset stats + offline masked EPE.

    # stats + masked EPE over a real capture tree
    python -m raftstereo_tpu.cli.sl --root datasets/SL \
        --restore_ckpt sl-final --input_mode sl

    # stats only (no model, no jax compile)
    python -m raftstereo_tpu.cli.sl --root datasets/SL --stats_only

Without ``--root`` the run scores the in-memory exact-GT synthetic SL set
(sl/synthetic.py) — the same data the certification and serving-parity
tests use.  The metrics are MASKED: EPE and bad-px are computed over the
valid-modulation region only (docs/structured_light.md), and with
``--batch_pad`` the evaluator executes at the serving engine's padded
program shape, so the printed numbers are bitwise-comparable to
``/predict`` answers.

The grown-up form of ``cli.sl_smoke`` (which remains as the bare dataset
round-trip check): this one speaks the train protocol, runs the model,
and prints one JSON line for scripting.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

from .common import load_variables, setup_logging

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    from ..config import add_model_args

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=None,
                   help="SL capture tree (data/sl.py layout); default: "
                        "the in-memory exact-GT synthetic set")
    p.add_argument("--split", default="validation",
                   help="capture-tree split to read (with --root)")
    p.add_argument("--scale", type=float, default=1.0,
                   help="image rescale factor for the capture tree")
    p.add_argument("--pairs", type=int, default=8,
                   help="synthetic pairs when --root is not given")
    p.add_argument("--hw", type=int, nargs=2, default=[64, 96],
                   metavar=("H", "W"),
                   help="synthetic pair size when --root is not given")
    p.add_argument("--stats_only", action="store_true",
                   help="print dataset stats and exit (no model run)")
    p.add_argument("--restore_ckpt", default=None,
                   help=".pth or Orbax weights (default: random weights — "
                        "smoke/dev only)")
    p.add_argument("--eval_iters", type=int, default=12,
                   help="GRU iterations per evaluated pair")
    p.add_argument("--bad_px", type=float, default=1.0,
                   help="bad-pixel threshold for the bad-px metric")
    p.add_argument("--batch_pad", type=int, default=None,
                   help="serving-parity mode: zero-pad the batch axis to "
                        "this size (the engine's max_batch_size) so "
                        "results match /predict bitwise")
    add_model_args(p)
    return p


def _build_dataset(args):
    """(train-protocol view, stats dict).  Stats come from the raw reader
    when a tree is given, so they describe the capture, not the view."""
    if args.root:
        from ..data.sl import StructuredLightDataset
        from ..sl import SLTrainView
        raw = StructuredLightDataset(args.root, split=args.split,
                                     scale=args.scale, with_depth=True)
        stats = {"source": args.root, "split": args.split,
                 "samples": len(raw), "num_patterns": raw.num_patterns}
        if len(raw) == 0:
            return None, stats
        _meta, left, _r, _f, valid = SLTrainView(raw)[0]
        stats.update(hw=list(left.shape[:2]),
                     channels=int(left.shape[-1]),
                     valid_frac=round(float(valid.mean()), 4))
        return SLTrainView(raw), stats
    from ..sl import SLShiftStereoDataset
    ds = SLShiftStereoDataset(n=args.pairs, hw=tuple(args.hw))
    _meta, left, _r, _f, valid = ds[0]
    stats = {"source": "synthetic", "samples": len(ds),
             "hw": list(left.shape[:2]), "channels": int(left.shape[-1]),
             "valid_frac": round(float(valid.mean()), 4)}
    return ds, stats


def main(argv=None) -> int:
    setup_logging()
    args = build_parser().parse_args(argv)

    dataset, stats = _build_dataset(args)
    logger.info("SL dataset: %s", stats)
    if dataset is None:
        logger.error("Dataset is empty — check --root layout "
                     "(see raftstereo_tpu/data/sl.py docstring)")
        return 1
    if args.stats_only:
        print(json.dumps(stats))
        return 0

    from ..config import model_config_from_args

    config = model_config_from_args(args)
    if config.input_mode != "sl":
        logger.error("masked-EPE evaluation needs an SL model — pass "
                     "--input_mode sl (got %r)", config.input_mode)
        return 2

    import jax

    from ..models import RAFTStereo
    from ..sl import masked_epe

    model = RAFTStereo(config)
    if args.restore_ckpt:
        variables = load_variables(args.restore_ckpt, config, model)
        logger.info("Loaded checkpoint %s", args.restore_ckpt)
    else:
        variables = model.init(jax.random.key(0), tuple(stats["hw"]))
        logger.warning("No --restore_ckpt: evaluating RANDOM weights "
                       "(smoke/dev only)")

    metrics, _preds = masked_epe(model, variables, dataset,
                                 iters=args.eval_iters,
                                 batch_pad=args.batch_pad,
                                 bad_px=args.bad_px)
    logger.info("SL masked metrics: %s", metrics)
    print(json.dumps({**stats, **metrics}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
