"""Command-line entry points, all consuming the single typed config
(vs the reference's four duplicated argparse blocks — SURVEY.md §2.6):

* ``python -m raftstereo_tpu.cli.train``     — training loop
* ``python -m raftstereo_tpu.cli.evaluate``  — benchmark validation
* ``python -m raftstereo_tpu.cli.demo``      — disparity inference + viz
* ``python -m raftstereo_tpu.cli.serve``     — dynamic-batching HTTP serving
  (+ ``--loadgen`` traffic driver; docs/serving.md); session-aware
  ``/predict`` for video streams (docs/streaming.md)
* ``python -m raftstereo_tpu.cli.stream``    — offline warm-start streaming
  runner: warm vs cold on a synthetic sequence (docs/streaming.md)
* ``python -m raftstereo_tpu.cli.sl``        — structured-light workload:
  dataset stats + offline masked-EPE run (docs/structured_light.md)
* ``python -m raftstereo_tpu.cli.sl_smoke``  — structured-light data check
* ``python -m raftstereo_tpu.cli.router``    — model-free cluster front-end
  over N backend servers (docs/serving.md "Cluster")
* ``python -m raftstereo_tpu.cli.certify``   — accuracy-tier certification
  manifest (docs/serving.md "Accuracy tiers")
* ``python -m raftstereo_tpu.cli.loadgen``   — trace-driven SLO harness:
  gen / replay / fit / whatif (docs/slo_harness.md)
* ``python -m raftstereo_tpu.cli.sessiontier`` — model-free durable
  session tier: any replica resumes any stream warm (docs/streaming.md
  "Durable sessions")
* ``python -m raftstereo_tpu.cli.obs``       — fleet observatory client:
  trace / fleet / alerts verbs against a running router
  (docs/observability.md "Fleet observatory")
"""
