"""Exact-GT synthetic structured light.

The passive workloads earn their deterministic EPE gates from
``data/synthetic.ShiftStereoDataset`` — learnable integer-shift scenes
with exact ground truth.  This module is the SL twin: the same
integer-shift construction, but the matchable texture comes from the
PROJECTOR, not the scene — the ambient pair is deliberately textureless
(flat gray), so a model can only drive masked EPE toward zero by using
the pattern channels through the learned SL front.  That property is what
makes the SL train-convergence gate (tests/test_sl.py) a genuine test of
pattern conditioning rather than of passive stereo wearing extra
channels.

Pattern battery per scene (all shifted consistently with the scene, so
``right(y) = left(y + d)`` holds channel-by-channel, exactly):

* pattern 0 — all-on reference (real rigs capture one for
  albedo/modulation estimation); after gating it IS the modulation gate,
  which is how the train view recovers ``valid`` (sl/adapter.py).
* patterns 1-4 — vertical stripes of distinct periods and random phase
  (the classic stripe/phase battery); any single stripe is ambiguous
  modulo its period, the battery jointly is not.
* patterns 5-8 — random binary speckle (the active-stereo speckle
  projector), locally unique along every epipolar line.

A configurable band of scene columns returns no projector light: the
modulation gate is zero there, patterns are dark, and the region is
excluded from ``valid`` — predictions there are unconstrained garbage,
which is exactly why the MASKED metrics matter (unmasked EPE on these
scenes is large; masked EPE trains to ~0).

Two forms, same construction math:

* :class:`SLShiftStereoDataset` — in-memory, items already in the
  train protocol with 12-channel stacks (tests, certification).
* :func:`make_learnable_sl` — on-disk, writing the ``data/sl.py`` capture
  tree layout (ambient_light/, pattern_k/, three_phase/, depth/) so the
  REAL reader + train view run end-to-end; depth is written as
  ``focal * baseline / d`` so the reader's depth->disparity conversion
  returns the integer shift to float32 precision.
"""

from __future__ import annotations

import os
from os.path import join

import numpy as np
from PIL import Image

from ..data.sl import SLCalibration
from .adapter import NUM_PATTERNS, stack_sl_inputs

__all__ = ["SLShiftStereoDataset", "make_learnable_sl"]

# Flat ambient gray level: textureless on purpose (see module docstring).
_AMBIENT_GRAY = 96.0
# Half-periods of the stripe patterns (distinct, so the battery jointly
# disambiguates shifts any single stripe aliases).
_STRIPE_HALF_PERIODS = (2, 3, 4, 6)


def _make_patterns(rng: np.random.Generator, h: int, span: int,
                   n: int = NUM_PATTERNS) -> np.ndarray:
    """(h, span, n) binary 0/1 projector patterns over the scene strip."""
    pats = [np.ones((h, span), np.float32)]  # pattern 0: all-on reference
    x = np.arange(span)
    for p in _STRIPE_HALF_PERIODS:
        phase = int(rng.integers(2 * p))
        row = (((x + phase) // p) % 2).astype(np.float32)
        pats.append(np.tile(row, (h, 1)))
    while len(pats) < n:
        pats.append((rng.random((h, span)) > 0.5).astype(np.float32))
    return np.stack(pats[:n], axis=-1)


def _scene(rng: np.random.Generator, hw, max_disp: int, invalid_band: int):
    """One integer-shift SL scene: returns (di, ambient_l, ambient_r,
    mask18, gate_l) with the dataset's right-channels-first mask order."""
    h, w = hw
    di = int(rng.integers(2, max_disp + 1))
    span = w + di
    pats = _make_patterns(rng, h, span)
    gate = np.ones((h, span), np.float32)
    if invalid_band:
        gate[:, :invalid_band] = 0.0  # no projector return here
    ambient = np.full((h, span, 3), _AMBIENT_GRAY, np.float32)
    # left(x) matches right(x - d): right(y) = left(y + d), per channel.
    amb_l, amb_r = ambient[:, :w], ambient[:, di:di + w]
    gate_l, gate_r = gate[:, :w], gate[:, di:di + w]
    pat_l = pats[:, :w] * gate_l[..., None]
    pat_r = pats[:, di:di + w] * gate_r[..., None]
    mask18 = np.concatenate([pat_r, pat_l], axis=-1).astype(np.float32)
    return di, amb_l, amb_r, mask18, gate_l


class SLShiftStereoDataset:
    """In-memory exact-GT SL pairs in the train protocol:
    ``(meta, left12, right12, flow(H,W,1), valid)``.

    The 12-channel stacks are built by :func:`~raftstereo_tpu.sl.adapter.
    stack_sl_inputs` — the same adapter every other consumer uses, so the
    items feed training, the offline evaluator and serving unchanged.
    ``valid`` is the modulation gate (zero over the projector-shadow
    band); ground truth is the integer shift, exact.
    """

    def __init__(self, n=16, hw=(64, 96), max_disp=8, seed=0,
                 invalid_band=6):
        rng = np.random.default_rng(seed)
        self._items = []
        self.disps = []
        for i in range(n):
            di, amb_l, amb_r, mask18, gate_l = _scene(
                rng, hw, max_disp, invalid_band)
            left, right = stack_sl_inputs(amb_l, amb_r, mask18)
            flow = np.full((*hw, 1), -float(di), np.float32)
            self._items.append((["sl", i], left, right, flow,
                                gate_l.astype(np.float32)))
            self.disps.append(di)

    def reseed(self, seed):  # loader protocol; the set is static
        pass

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i % len(self._items)]


def make_learnable_sl(root, scenes=("sceneA",), poses=("0001",),
                      hw=(64, 96), max_disp=8, invalid_band=6,
                      calibration: SLCalibration = SLCalibration(),
                      rng=None):
    """Learnable exact-GT SL capture tree in the ``data/sl.py`` layout.

    The on-disk twin of :class:`SLShiftStereoDataset` (same construction,
    different transport), the way ``make_learnable_kitti`` twins
    ``ShiftStereoDataset`` for the passive pipeline: reading it back
    through ``StructuredLightDataset(with_depth=True, scale=1.0)`` + the
    SL train view reproduces integer-shift ground truth to float32
    precision, including the modulation gate.

    Three-phase images are constant per side — equal brightness (zero
    modulation) over the invalid band, 60-gray-level phase steps
    elsewhere, so the reader's validation threshold 5.0 AND any training
    threshold ``|10 + 9·N(0,1)|`` both reproduce the written gate.
    """
    rng = rng or np.random.default_rng(0)
    root = str(root)
    h, w = hw
    num = calibration.focal * calibration.baseline
    for scene in scenes:
        for pose in poses:
            di, amb_l, amb_r, mask18, _gate_l = _scene(
                rng, hw, max_disp, invalid_band)
            amb = join(root, scene, "ambient_light")
            os.makedirs(amb, exist_ok=True)
            for side, img in (("L", amb_l), ("R", amb_r)):
                Image.fromarray(img.astype(np.uint8)).save(
                    join(amb, f"{pose}_{side}.png"))
            tp = join(root, scene, "three_phase")
            os.makedirs(tp, exist_ok=True)
            gates = {"l": mask18[..., NUM_PATTERNS],  # left pattern 0
                     "r": mask18[..., 0]}             # right pattern 0
            for side, gate in gates.items():
                for i in range(3):
                    img = np.where(gate > 0.5, 100 + 60 * i, 100)
                    Image.fromarray(img.astype(np.uint8)).save(
                        join(tp, f"{pose}_tp{i + 1}_{side}.png"))
            for k in range(NUM_PATTERNS):
                pd = join(root, scene, f"pattern_{k}")
                os.makedirs(pd, exist_ok=True)
                # The stored stack is already gated; re-lighting the
                # shadow band would not survive the reader's gate anyway,
                # so write the gated masks as the capture.
                for side, ch in (("l", NUM_PATTERNS + k), ("r", k)):
                    Image.fromarray(
                        (mask18[..., ch] * 255).astype(np.uint8)).save(
                        join(pd, f"{pose}_B_{side}.png"))
            dp = join(root, scene, "depth")
            os.makedirs(dp, exist_ok=True)
            depth = np.full((h, w), num / di, np.float32)
            for side in ("L", "R"):
                np.save(join(dp, f"{pose}_depth_{side}.npy"), depth)
