"""Structured light as a first-class workload (docs/structured_light.md).

The data layer (data/sl.py) already reads real SL capture trees — ambient
pair, 9 projected-pattern masks per side, three-phase modulation gating,
depth-derived disparity.  This package makes that modality trainable,
certifiable and servable:

* :mod:`adapter`   — the pattern-conditioning front: stacks the gated
  pattern channels onto the ambient pair as 12-channel model inputs
  (``RAFTStereoConfig.input_mode == "sl"``), plus the train-protocol view
  whose ``valid`` mask folds the modulation gate into the sequence loss.
* :mod:`synthetic` — exact-GT synthetic SL: projected stripe/speckle
  patterns over integer-shift scenes, in-memory
  (:class:`~raftstereo_tpu.sl.synthetic.SLShiftStereoDataset`) and
  on-disk in the ``data/sl.py`` tree layout
  (:func:`~raftstereo_tpu.sl.synthetic.make_learnable_sl`).
* :mod:`evaluate`  — the offline masked-EPE/bad-px evaluator, with a
  serving-parity mode whose disparities are bitwise-identical to
  ``/predict`` answers (tests/test_sl.py).
"""

from .adapter import NUM_PATTERNS, SL_CHANNELS, SLTrainView, stack_sl_inputs
from .evaluate import masked_epe
from .synthetic import SLShiftStereoDataset, make_learnable_sl

__all__ = [
    "NUM_PATTERNS", "SL_CHANNELS", "SLTrainView", "stack_sl_inputs",
    "masked_epe", "SLShiftStereoDataset", "make_learnable_sl",
]
