"""Pattern-conditioning input adapter for the structured-light workload.

``data/sl.py`` emits per sample an ambient stereo pair plus an 18-channel
gated pattern stack (``num_patterns`` RIGHT channels first, then the LEFT
channels — that order is the dataset's contract, data/sl.py:143-152).
The model consumes SL input as one 12-channel image per side: ambient RGB
plus that side's 9 pattern channels, projected down to the encoders'
3-channel input by a learned front (models/raft_stereo.SLProjection,
``RAFTStereoConfig.input_mode == "sl"``).

This module owns the stacking convention.  Every consumer — the train
view below, the offline evaluator (sl/evaluate.py), serving clients, the
certification path (eval/certify.py) — builds its 12-channel stacks HERE,
which is what makes offline and ``/predict`` results comparable bitwise.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# Projected patterns per side in the SL capture layout (data/sl.py).
NUM_PATTERNS = 9
# Channels per 12-channel model input: ambient RGB + that side's patterns.
SL_CHANNELS = 3 + NUM_PATTERNS


def stack_sl_inputs(img_l: np.ndarray, img_r: np.ndarray,
                    mask18: np.ndarray,
                    num_patterns: int = NUM_PATTERNS
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Build the (left, right) 12-channel model inputs from one SL sample.

    ``mask18`` is the dataset's gated 0/1 pattern stack, RIGHT channels
    first (data/sl.py).  The binary masks are scaled to 0/255 so the
    model's uniform ``x / 255 * 2 - 1`` input normalization
    (models/raft_stereo._encode) treats pattern channels exactly like the
    ambient ones — no per-channel special case anywhere downstream.
    """
    mask18 = np.asarray(mask18, np.float32)
    assert mask18.shape[-1] == 2 * num_patterns, (
        f"pattern stack has {mask18.shape[-1]} channels, expected "
        f"{2 * num_patterns} ({num_patterns} right + {num_patterns} left)")
    pats_r = mask18[..., :num_patterns] * 255.0
    pats_l = mask18[..., num_patterns:] * 255.0
    left = np.concatenate([np.asarray(img_l, np.float32), pats_l], axis=-1)
    right = np.concatenate([np.asarray(img_r, np.float32), pats_r], axis=-1)
    return left, right


class SLTrainView:
    """Train-protocol view over ``StructuredLightDataset(with_depth=True)``:
    items are ``(meta, left12, right12, flow_px, valid)``.

    * ``left12``/``right12`` come from :func:`stack_sl_inputs` — the same
      stacks serving and the offline evaluator consume.
    * ``flow_px`` is the left->right disparity in the framework's
      negative-x-flow pixel convention (core/stereo_datasets.py:77).
    * ``valid`` folds the MODULATION GATE into depth validity, so the
      standard masked sequence loss (train/step.sequence_loss) scores only
      the valid-modulation region — the SL masked loss needs no new loss
      code.  The gate is read from the left pattern-0 channel: SL rigs
      project an all-on reference pattern first (sl/synthetic.py writes
      one; real captures use it for albedo/modulation estimation), so
      after the dataset's thresholding that channel IS the 0/1 gate.

    Cropping mirrors ``data/sl.SLStereoView``: fixed-size random crops for
    static jitted shapes; no photometric augmentation (it would destroy
    the projected-pattern structure the masks encode).
    """

    def __init__(self, dataset, crop_size: Optional[Tuple[int, int]] = None):
        assert dataset.with_depth, "SL train view needs with_depth=True"
        self._ds = dataset
        self.crop_size = tuple(crop_size) if crop_size else None
        self.rng = np.random.default_rng(0)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)
        self._ds.reseed(seed)

    def __len__(self) -> int:
        return len(self._ds)

    def __getitem__(self, index: int):
        img_l, img_r, mask18, disparity, depth_mask = self._ds[index]
        n = self._ds.num_patterns
        left, right = stack_sl_inputs(img_l, img_r, mask18, n)
        w = disparity.shape[1]
        flow = (-disparity[..., 1:2] * w).astype(np.float32)  # px, negative
        gate = mask18[..., n]  # left pattern 0 = all-on reference
        valid = (depth_mask[..., 1] * gate).astype(np.float32)
        meta = list(self._ds.samples[index])
        if self.crop_size is not None:
            ch, cw = self.crop_size
            h, w_ = left.shape[:2]
            if h < ch or w_ < cw:
                raise ValueError(f"SL frame {h}x{w_} smaller than crop "
                                 f"{ch}x{cw}; lower crop_size or raise scale")
            y0 = int(self.rng.integers(0, h - ch + 1))
            x0 = int(self.rng.integers(0, w_ - cw + 1))
            sl = np.s_[y0:y0 + ch, x0:x0 + cw]
            left, right = left[sl], right[sl]
            flow, valid = flow[sl], valid[sl]
        return meta, left, right, flow, valid
