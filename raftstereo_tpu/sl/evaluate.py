"""Offline masked-EPE evaluation for the structured-light workload.

Scores a model over train-protocol SL items — ``(meta, left12, right12,
flow_px, valid)`` from :class:`~raftstereo_tpu.sl.synthetic.
SLShiftStereoDataset` or :class:`~raftstereo_tpu.sl.adapter.SLTrainView` —
reporting EPE and bad-px ONLY over the valid-modulation region.  The
projector-shadow band carries no pattern signal, so predictions there are
unconstrained; unmasked metrics on SL scenes are meaningless by design
(sl/synthetic.py module docstring).

Serving parity: pass ``batch_pad=engine.max_batch_size`` (plus the
engine's ``divis_by``/``bucket_multiple``) and the underlying
:class:`~raftstereo_tpu.eval.runner.Evaluator` executes each pair at the
serving engine's padded program shape, making the returned disparities
bitwise-identical to ``/predict`` answers for the same stacks — the SL
serving acceptance gate (tests/test_sl.py) is this comparison.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..eval.runner import Evaluator

__all__ = ["masked_epe"]


def masked_epe(model, variables, dataset, iters: int = 32, *,
               divis_by: int = 32, bucket_multiple=None, batch_pad=None,
               bad_px: float = 1.0
               ) -> Tuple[Dict[str, float], List[np.ndarray]]:
    """Masked EPE / bad-px over an SL dataset.

    Returns ``(metrics, preds)``: metrics has ``epe``, ``bad{bad_px}``
    (fraction of valid pixels with error > ``bad_px``), ``valid_frac`` and
    ``n``; preds holds each pair's full (H, W) disparity map so callers
    (cli/sl.py, serving-parity tests) can inspect per-pixel output.
    """
    evaluator = Evaluator(model, variables, iters=iters, divis_by=divis_by,
                          bucket_multiple=bucket_multiple,
                          batch_pad=batch_pad)
    errs, valids, preds = [], [], []
    for i in range(len(dataset)):
        _meta, left, right, flow, valid = dataset[i]
        pred = np.asarray(evaluator(left, right))
        preds.append(pred)
        errs.append(np.abs(pred - flow[..., 0]))
        valids.append(np.asarray(valid, np.float32))
    err = np.stack(errs)
    valid = np.stack(valids)
    n_valid = max(float(valid.sum()), 1.0)
    metrics = {
        "epe": float((err * valid).sum() / n_valid),
        f"bad{bad_px:g}": float(((err > bad_px) * valid).sum() / n_valid),
        "valid_frac": float(valid.mean()),
        "n": float(len(preds)),
    }
    return metrics, preds
