"""Spatial sharding: ONE inference split over the ``space`` mesh axis.

Every serving path before this file is single-chip per request (replicas,
sessions, tiers all schedule WHOLE engines); this module runs a single
stereo pair with image height H sharded across the ``space`` axis of a
``(1, N)`` mesh under ``shard_map`` — the path for pairs whose correlation
pyramid and activations exceed one chip's HBM.  RAFT-Stereo's structure
makes H the free axis: the all-pairs correlation is 1-D along W (each H
row's epipolar line is self-contained, so corr build AND lookups are
row-local per shard), and everything else is convs with small receptive
fields.  Feature extraction, the corr volume, and the whole GRU iteration
loop therefore stay sharded end to end; the only data that ever crosses
shard boundaries is

* receptive-field-sized halo rows, exchanged by ``ppermute`` before each
  conv (``halo_exchange``): every shard sends its top/bottom ``pad`` rows
  to its neighbors, convolves VALID-in-H over the extended slab, and gets
  back exactly its own output rows.  ``ppermute`` zero-fills the shards
  with no neighbor, which reproduces the reference conv's zero padding at
  the global image edges bit-for-bit — one mechanism covers interior and
  edge slabs;
* full-height all-gathers for the two genuinely global ops: instance-norm
  statistics (a mean over all of H x W — stats are computed on the
  gathered activation via ``models.layers.instance_norm_stats`` and
  applied to the local slab, the exact split that function exists for)
  and the cross-GRU-level bilinear resizes (align-corners row weights
  couple distant rows; v1 gathers the COARSE level, which is 1/64th of
  the finest activation, and slices the local slab from the exact
  reference resize);
* full-height all-gathers for convs whose LOCAL output is tiny
  (``SPATIAL_REPLICATE_BELOW``): XLA:CPU's Eigen contraction shards the
  reduction dimension across threads when a gemm's output is small,
  combining per-thread partial sums whose rounding depends on the output
  shape — so a slab-height conv can round differently from the
  full-height conv even though every window sees identical inputs.
  Those convs run replicated at full height (reference-identical shape
  forces reference-identical accumulation) and slice the shard's rows
  back out; coarse pyramid levels are 1/4..1/64 of the trunk pixels, so
  the replicated compute is noise at serving resolutions.

Bitwise contract: on the CPU fp32 path the sharded forward is
bit-identical to ``RAFTStereo.jitted_infer`` / ``jitted_infer_init`` at
the same resolution (asserted on a real ``(1, 4)`` virtual-device mesh in
tests/test_spatial_sharding.py).  Per-op equivalences: a halo-exchanged
VALID-in-H conv equals the zero-padded full conv at stride 1 and at
stride 2 (even local H); frozen batch norm is elementwise, so the real
flax module applied to the slab matches; the 3x3/s2/p1 average pool over
a halo-extended slab matches; convex upsampling reads a 3x3 coarse
neighbourhood, one halo row.

v1 scope (validated in ``validate_spatial_config``):

* XLA GRU step only (``gru_backend="xla"``; "auto" is accepted where it
  resolves to XLA).  The Pallas megakernel (ops/pallas_gru.py) is a bare
  ``pallas_call`` that cannot run under ``shard_map`` today — the sharded
  megakernel is the documented follow-up (ROADMAP.md).  Likewise the
  Pallas corr backends remap to their XLA twins (pallas -> reg,
  pallas_alt -> alt: same math, different kernels), and the plain conv
  flow head / plain stem are always used — so on TPU the spatial path's
  numerics match the CPU certified-parity path, not the single-chip TPU
  fast paths (tap head, fused stem, corr epilogue).
* no int8 corr (``corr_quant``), no ``shared_backbone``, no GroupNorm
  context (the default "batch" and "instance"/"none" are covered).

Geometry: each shard's slab must stay evenly divisible through every
stride-2 stage and the convex upsample, i.e. H % (shards *
``spatial_row_multiple(cfg)``) == 0 — the serving layer sizes its
spatial buckets to this (serve/spatial/).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..config import RAFTStereoConfig
from ..models.layers import instance_norm_apply, instance_norm_stats
from ..ops.corr import make_corr_fn, resolve_implementation
from ..ops.image import coords_grid_x, resize_bilinear_align_corners
from .mesh import SPACE_AXIS, make_mesh


# Below this many LOCAL conv-output elements, the slab conv is computed on
# the all-gathered full-height input instead of the halo-extended slab
# (module docstring: Eigen shards the gemm reduction dimension for small
# outputs, making the rounding output-shape-dependent).  Empirically the
# slab/full split is bitwise-stable from 12288 elements up and diverges at
# <= 6144 on an 8-virtual-device host; 32768 gives > 5x margin.  Env
# override for hosts whose Eigen heuristics draw the line elsewhere.
SPATIAL_REPLICATE_BELOW = int(os.environ.get(
    "RAFTSTEREO_SPATIAL_REPLICATE_BELOW", "32768"))


class SpatialShardingUnsupported(ValueError):
    """A config/shape the spatial v1 path cannot run.  Raised at setup or
    trace time, never mid-inference — the serving admission layer maps it
    to a 400 (serve/spatial/admission.py), so an unsupported request can
    never trigger a compile."""


# --------------------------------------------------------------- validation

def spatial_row_multiple(cfg: RAFTStereoConfig) -> int:
    """Per-shard slab-height granularity: the local trunk rows must divide
    evenly through every context-encoder stride-2 stage (2^(n_gru_layers-1))
    and the slab image rows through the trunk downsample (``factor``)."""
    return cfg.factor * 2 ** (cfg.n_gru_layers - 1)


def validate_spatial_config(cfg: RAFTStereoConfig) -> None:
    """Reject configs the v1 sharded forward does not cover (module
    docstring).  Cheap and pure — admission calls it per request."""
    from ..ops.pallas_gru import use_fused_gru

    if cfg.shared_backbone:
        raise SpatialShardingUnsupported(
            "spatial sharding does not support shared_backbone")
    if cfg.context_norm == "group":
        raise SpatialShardingUnsupported(
            "spatial sharding supports context_norm batch/instance/none, "
            "not group")
    if cfg.corr_quant:
        raise SpatialShardingUnsupported(
            "spatial sharding does not support the int8 corr volume "
            "(corr_quant); use an unquantized config")
    if use_fused_gru(cfg.gru_backend, test_mode=True):
        raise SpatialShardingUnsupported(
            "spatial sharding is XLA-GRU only in v1: set gru_backend=xla "
            "(the fused megakernel is a bare pallas_call and cannot be "
            "partitioned under shard_map)")


def check_spatial_shape(cfg: RAFTStereoConfig, shards: int, h: int,
                        w: int) -> None:
    """Static shape admission: H must split into ``shards`` equal slabs,
    each a multiple of ``spatial_row_multiple``."""
    if shards < 1:
        raise SpatialShardingUnsupported(f"shards must be >= 1, got {shards}")
    m = spatial_row_multiple(cfg) * shards
    if h % m:
        raise SpatialShardingUnsupported(
            f"spatial sharding needs H % {m} == 0 "
            f"({shards} shards x row multiple {spatial_row_multiple(cfg)}); "
            f"got H={h}")
    if w % cfg.factor:
        raise SpatialShardingUnsupported(
            f"W must be divisible by factor={cfg.factor}; got W={w}")


def spatial_corr_implementation(cfg: RAFTStereoConfig) -> str:
    """The corr backend the sharded forward uses: the config's resolved
    implementation with the Pallas kernels remapped to their XLA twins
    (identical math; the kernels are bare pallas_calls — module
    docstring)."""
    resolved = resolve_implementation(cfg.corr_implementation, quant=False)
    return {"pallas": "reg", "pallas_alt": "alt"}.get(resolved, resolved)


def spatial_mesh(shards: int, devices: Optional[Sequence] = None) -> Mesh:
    """The canonical spatial mesh: ``(1, shards)`` over the first
    ``shards`` devices — batch stays whole, H splits over ``space``
    (mesh.spatial_sharded is the matching NamedSharding)."""
    devices = list(devices if devices is not None else jax.devices())
    return make_mesh(data=1, space=shards, devices=devices[:shards])


# ------------------------------------------------------------ halo exchange

def halo_exchange(x: jax.Array, pad: int, n_shards: int,
                  axis_name: str = SPACE_AXIS) -> jax.Array:
    """Extend a local H slab (B, h, W, C) -> (B, h + 2*pad, W, C) with the
    neighbors' edge rows: shard i receives shard i-1's bottom ``pad`` rows
    above its slab and shard i+1's top rows below.  The boundary shards
    have no neighbor on one side; ``ppermute`` zero-fills unaddressed
    outputs, which is EXACTLY the reference conv's zero padding at the
    global top/bottom edge — so a VALID-in-H conv over the extended slab
    reproduces the padded full-image conv's rows bit-for-bit on every
    shard.  ``n_shards == 1`` degenerates to plain zero padding."""
    if pad == 0:
        return x
    if n_shards == 1:
        return jnp.pad(x, ((0, 0), (pad, pad), (0, 0), (0, 0)))
    down = [(i, i + 1) for i in range(n_shards - 1)]  # i's bottom -> i+1's top
    up = [(i + 1, i) for i in range(n_shards - 1)]    # i+1's top -> i's bottom
    top = lax.ppermute(x[:, -pad:], axis_name, down)
    bot = lax.ppermute(x[:, :pad], axis_name, up)
    return jnp.concatenate([top, x, bot], axis=1)


# ------------------------------------------------- sharded layer primitives
#
# Each helper mirrors ONE module apply from models/ as the raw lax call the
# flax module lowers to (fp32: promote_dtype is a no-op and flax's conv IS
# lax.conv_general_dilated at default precision + a bias broadcast), with
# the H padding moved from the conv into the halo exchange.  Parameters are
# indexed straight off the model's params tree — same names, same trees.

def _replicate_rows(x: jax.Array, n_sh: int,
                    fn: Callable[[jax.Array], jax.Array]) -> jax.Array:
    """Run ``fn`` on the full-height gather of a local slab and slice this
    shard's output rows back out.  ``fn`` sees the exact global array the
    reference forward sees, so its result is reference-bitwise no matter
    how the backend lowers it."""
    full = lax.all_gather(x, SPACE_AXIS, axis=1, tiled=True)
    y = fn(full)
    h_loc = y.shape[1] // n_sh
    i = lax.axis_index(SPACE_AXIS)
    return lax.dynamic_slice_in_dim(y, i * h_loc, h_loc, axis=1)


def _small_conv_output(x: jax.Array, k: jax.Array, stride: int, pad_h: int,
                       pad_w: int, n_sh: int) -> bool:
    """True when the LOCAL output of a slab conv falls under
    ``SPATIAL_REPLICATE_BELOW`` — the regime where Eigen's
    reduction-dimension sharding makes slab and full convs round
    differently (module docstring)."""
    if n_sh == 1:
        return False
    b, h, w = x.shape[:3]
    out_h = (h + 2 * pad_h - k.shape[0]) // stride + 1
    out_w = (w + 2 * pad_w - k.shape[1]) // stride + 1
    return b * out_h * out_w * k.shape[3] < SPATIAL_REPLICATE_BELOW


def _conv(p: Dict, x: jax.Array, stride: int, pad: int,
          n_sh: int) -> jax.Array:
    """``layers.conv`` (torch-geometry nn.Conv) on an H slab: halo rows in,
    VALID-in-H / symmetric-W conv out.  Stride 2 requires even local H
    (enforced by ``check_spatial_shape``); the slab's output rows then
    align exactly with the full conv's (first window of shard i starts at
    global row i*h_loc - pad, the same alignment the padded full conv
    gives row i*h_loc/stride).  Small outputs replicate at full height
    instead (``_small_conv_output``)."""
    k = p["kernel"].astype(x.dtype)
    b_ = p["bias"].astype(x.dtype)
    dn = ("NHWC", "HWIO", "NHWC")
    if _small_conv_output(x, k, stride, pad, pad, n_sh):
        return _replicate_rows(x, n_sh, lambda full: lax.conv_general_dilated(
            full, k, (stride, stride), ((pad, pad), (pad, pad)),
            dimension_numbers=dn) + b_)
    y = lax.conv_general_dilated(
        halo_exchange(x, pad, n_sh), k, (stride, stride),
        ((0, 0), (pad, pad)), dimension_numbers=dn)
    return y + b_


def _conv_slice(p: Dict, x: jax.Array, lo: int, hi: Optional[int],
                pad: int, bias: bool, n_sh: int) -> jax.Array:
    """``update._sliced_conv`` on a local H slab: conv by an input-channel
    slice of the kernel (the GRU's concat-free gate form), halo rows in /
    VALID-in-H out, with the same small-output replication as ``_conv``."""
    k = p["kernel"][:, :, lo:hi].astype(x.dtype)
    b_ = p["bias"].astype(x.dtype) if bias else None
    dn = ("NHWC", "HWIO", "NHWC")

    def apply(a: jax.Array, pad_h) -> jax.Array:
        y = lax.conv_general_dilated(a, k, (1, 1), (pad_h, (pad, pad)),
                                     dimension_numbers=dn)
        return y + b_ if bias else y

    if _small_conv_output(x, k, 1, pad, pad, n_sh):
        return _replicate_rows(x, n_sh, lambda full: apply(full, (pad, pad)))
    return apply(halo_exchange(x, pad, n_sh), (0, 0))


def _norm(nf: str, p: Dict, s: Dict, name: str, dtype, x: jax.Array,
          n_sh: int) -> jax.Array:
    """One norm site from ``layers.make_norm``.  Frozen batch norm is
    elementwise, so the real flax module on the local slab matches the
    full-image rows; instance norm gathers the full-height activation for
    its (H, W) statistics and normalizes the slab locally — the
    stats/apply split in models/layers.py exists for exactly this call
    (the lane-group factor k depends only on (C, W), so the slab shares
    the full image's view geometry)."""
    if nf == "none":
        return x
    if nf == "batch":
        return nn.BatchNorm(use_running_average=True, epsilon=1e-5,
                            dtype=dtype).apply(
            {"params": p[name], "batch_stats": s[name]}, x)
    if nf == "instance":
        full = (lax.all_gather(x, SPACE_AXIS, axis=1, tiled=True)
                if n_sh > 1 else x)
        k, mw, sw = instance_norm_stats(full)
        return instance_norm_apply(x, k, mw, sw)
    raise SpatialShardingUnsupported(f"unsupported norm under spatial: {nf}")


def _res_block(p: Dict, s: Dict, nf: str, dtype, x: jax.Array, stride: int,
               n_sh: int) -> jax.Array:
    """``layers.ResidualBlock``; the projection shortcut exists iff the
    params tree has one (stride != 1 or a channel change — mirrors
    ``has_projection``)."""
    y = nn.relu(_norm(nf, p, s, "norm1", dtype,
                      _conv(p["conv1"], x, stride, 1, n_sh), n_sh))
    y = nn.relu(_norm(nf, p, s, "norm2", dtype,
                      _conv(p["conv2"], y, 1, 1, n_sh), n_sh))
    if "downsample_conv" in p:
        x = _norm(nf, p, s, "downsample_norm", dtype,
                  _conv(p["downsample_conv"], x, stride, 0, n_sh), n_sh)
    return nn.relu(x + y)


def _trunk(p: Dict, s: Dict, nf: str, dtype, d: int, x: jax.Array,
           n_sh: int) -> jax.Array:
    """The shared encoder trunk (encoders._plain_stem + layer2/layer3),
    stride placement per the downsample-factor logic.  Always the PLAIN
    module path — the fused Pallas stem is single-chip-only, and plain is
    what the CPU reference runs, so the bitwise contract holds."""
    x = nn.relu(_norm(nf, p, s, "norm1", dtype,
                      _conv(p["conv1"], x, 1 + (d > 2), 3, n_sh), n_sh))
    x = _res_block(p["layer1_0"], s.get("layer1_0", {}), nf, dtype, x, 1, n_sh)
    x = _res_block(p["layer1_1"], s.get("layer1_1", {}), nf, dtype, x, 1, n_sh)
    x = _res_block(p["layer2_0"], s.get("layer2_0", {}), nf, dtype, x,
                   1 + (d > 1), n_sh)
    x = _res_block(p["layer2_1"], s.get("layer2_1", {}), nf, dtype, x, 1, n_sh)
    x = _res_block(p["layer3_0"], s.get("layer3_0", {}), nf, dtype, x,
                   1 + (d > 0), n_sh)
    x = _res_block(p["layer3_1"], s.get("layer3_1", {}), nf, dtype, x, 1, n_sh)
    return x


def _basic_encoder(p: Dict, s: Dict, nf: str, dtype, d: int, x: jax.Array,
                   n_sh: int) -> jax.Array:
    """``encoders.BasicEncoder`` (the feature net, instance norm)."""
    x = _trunk(p, s, nf, dtype, d, x, n_sh)
    return _conv(p["conv2"], x, 1, 0, n_sh)


def _multi_encoder(p: Dict, s: Dict, nf: str, dtype, d: int, x: jax.Array,
                   num_layers: int, n_heads: int,
                   n_sh: int) -> List[List[jax.Array]]:
    """``encoders.MultiBasicEncoder`` (the context net): trunk + per-level
    heads, finest first — out[level][head]."""
    x = _trunk(p, s, nf, dtype, d, x, n_sh)

    def head_rc(prefix: str, hi: int, y: jax.Array) -> jax.Array:
        y = _res_block(p[f"{prefix}_{hi}_res"],
                       s.get(f"{prefix}_{hi}_res", {}), nf, dtype, y, 1, n_sh)
        return _conv(p[f"{prefix}_{hi}_conv"], y, 1, 1, n_sh)

    outputs = [[head_rc("head08", hi, x) for hi in range(n_heads)]]
    if num_layers >= 2:
        y = _res_block(p["layer4_0"], s.get("layer4_0", {}), nf, dtype, x, 2,
                       n_sh)
        y = _res_block(p["layer4_1"], s.get("layer4_1", {}), nf, dtype, y, 1,
                       n_sh)
        outputs.append([head_rc("head16", hi, y) for hi in range(n_heads)])
    if num_layers >= 3:
        z = _res_block(p["layer5_0"], s.get("layer5_0", {}), nf, dtype, y, 2,
                       n_sh)
        z = _res_block(p["layer5_1"], s.get("layer5_1", {}), nf, dtype, z, 1,
                       n_sh)
        outputs.append([_conv(p[f"head32_{hi}_conv"], z, 1, 1, n_sh)
                        for hi in range(n_heads)])
    return outputs


def _gru(p: Dict, h: jax.Array, cz, cr, cq, x: jax.Array,
         n_sh: int) -> jax.Array:
    """``update.ConvGRU``'s apply-time sliced form (kernel[:, :, :hd] on h,
    the rest on x, summed), each conv halo-exchanged."""
    hd = h.shape[-1]
    zr = (_conv_slice(p["convzr"], h, 0, hd, 1, False, n_sh)
          + _conv_slice(p["convzr"], x, hd, None, 1, True, n_sh))
    z = nn.sigmoid(zr[..., :hd] + cz)
    r = nn.sigmoid(zr[..., hd:] + cr)
    q = (_conv_slice(p["convq"], r * h, 0, hd, 1, False, n_sh)
         + _conv_slice(p["convq"], x, hd, None, 1, True, n_sh))
    q = nn.tanh(q + cq)
    return (1 - z) * h + z * q


def _motion_encoder(p: Dict, flow: jax.Array, corr: jax.Array, dtype,
                    n_sh: int) -> jax.Array:
    """``update.BasicMotionEncoder`` (no corr-epilogue preact — spatial
    never fuses convc1 into a lookup kernel).  convc1 is the pointwise
    padded conv (kernel zero-padded to the corr width); convf1 keeps the
    bf16 x-slice contraction gate."""
    k = p["convc1"]["kernel"]
    padc = corr.shape[-1] - k.shape[2]
    if padc:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, padc), (0, 0)))
    xc = corr.astype(dtype)
    kc = k.astype(dtype)
    bc = p["convc1"]["bias"].astype(xc.dtype)

    def c1_fn(a: jax.Array) -> jax.Array:
        y = lax.conv_general_dilated(
            a, kc, (1, 1), ((0, 0), (0, 0)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + bc

    if _small_conv_output(xc, kc, 1, 0, 0, n_sh):
        c1 = nn.relu(_replicate_rows(xc, n_sh, c1_fn))
    else:
        c1 = nn.relu(c1_fn(xc))
    cor = nn.relu(_conv(p["convc2"], c1, 1, 1, n_sh))
    if dtype == jnp.bfloat16:
        f1 = _conv_slice(p["convf1"], flow[..., :1], 0, 1, 3, True, n_sh)
    else:
        f1 = _conv(p["convf1"], flow, 1, 3, n_sh)
    flo = nn.relu(_conv(p["convf2"], nn.relu(f1), 1, 1, n_sh))
    out = nn.relu(_conv(p["conv"], jnp.concatenate([cor, flo], axis=-1),
                        1, 1, n_sh))
    return jnp.concatenate([out, flow], axis=-1)


def _avg_pool2x(x: jax.Array, n_sh: int) -> jax.Array:
    """``image.avg_pool2x`` (3x3/s2/p1, zeros in the divisor) on a slab:
    one halo row each way, VALID-in-H windows."""
    ext = halo_exchange(x, 1, n_sh)
    s = lax.reduce_window(
        ext, 0.0, lax.add,
        window_dimensions=(1, 3, 3, 1), window_strides=(1, 2, 2, 1),
        padding=((0, 0), (0, 0), (1, 1), (0, 0)))
    return s / jnp.asarray(9.0, dtype=x.dtype)


def _interp_to(x: jax.Array, dest: jax.Array, n_sh: int) -> jax.Array:
    """``update._interp_to`` (align-corners bilinear to dest's (H, W)):
    align-corners row weights couple rows across slab boundaries with
    H-dependent (not receptive-field) reach, so v1 gathers the COARSE
    source level (1/4 the rows of dest, itself already 1/factor of the
    image), runs the exact reference resize at full height, and slices
    this shard's rows — bitwise by construction.  A halo-based resize is
    the documented follow-up alongside the sharded megakernel."""
    h_loc, w = dest.shape[1:3]
    if n_sh == 1:
        return resize_bilinear_align_corners(x, (h_loc, w))
    full = lax.all_gather(x, SPACE_AXIS, axis=1, tiled=True)
    out = resize_bilinear_align_corners(full, (h_loc * n_sh, w))
    i = lax.axis_index(SPACE_AXIS)
    return lax.dynamic_slice_in_dim(out, i * h_loc, h_loc, axis=1)


def _flow_head(p: Dict, x: jax.Array, n_sh: int) -> jax.Array:
    """``update.FlowHead``, always the plain-conv form (the tap-matmul
    head is a single-chip TPU layout fix; plain is the CPU certified
    path)."""
    y = nn.relu(_conv(p["conv1"], x, 1, 1, n_sh))
    return _conv(p["conv2"], y, 1, 1, n_sh)


def _convex_upsample(flow: jax.Array, mask: jax.Array, factor: int,
                     n_sh: int) -> jax.Array:
    """``ops.upsample.convex_upsample``: softmax over each pixel's 3x3
    coarse neighbourhood — one halo row of the scaled flow replaces the
    H zero-pad of ``extract_3x3_patches``; the mask softmax is
    pixel-local."""
    b, h, w, d = flow.shape
    mask = mask.reshape(b, h, w, 9, factor, factor).astype(jnp.float32)
    mask = jax.nn.softmax(mask, axis=3)
    ext = halo_exchange(flow.astype(jnp.float32) * factor, 1, n_sh)
    pw = jnp.pad(ext, ((0, 0), (0, 0), (1, 1), (0, 0)))
    rows = [pw[:, ky:ky + h, kx:kx + w, :]
            for ky in range(3) for kx in range(3)]
    patches = jnp.stack(rows, axis=3)
    up = jnp.einsum("bhwkd,bhwkyx->bhywxd", patches, mask)
    return up.reshape(b, h * factor, w * factor, d)


# ------------------------------------------------------- sharded forward

def _update_block(up: Dict, cfg: RAFTStereoConfig, dtype, n_sh: int,
                  net: Sequence[jax.Array], zqr: Sequence[Tuple],
                  corr: Optional[jax.Array] = None,
                  flow: Optional[jax.Array] = None,
                  iter0: bool = True, iter1: bool = True, iter2: bool = True,
                  update: bool = True):
    """``update.BasicMultiUpdateBlock.__call__`` (test-mode, no in-loop
    mask head), coarsest -> finest with pooled finer / upsampled coarser
    cross-level inputs."""
    n = cfg.n_gru_layers
    net = list(net)
    if n == 3 and iter2:
        net[2] = _gru(up["gru2"], net[2], *zqr[2],
                      _avg_pool2x(net[1], n_sh), n_sh)
    if n >= 2 and iter1:
        if n > 2:
            x1 = jnp.concatenate([_avg_pool2x(net[0], n_sh),
                                  _interp_to(net[2], net[1], n_sh)], axis=-1)
        else:
            x1 = _avg_pool2x(net[0], n_sh)
        net[1] = _gru(up["gru1"], net[1], *zqr[1], x1, n_sh)
    if iter0:
        mf = _motion_encoder(up["encoder"], flow, corr, dtype, n_sh)
        if n > 1:
            x0 = jnp.concatenate([mf, _interp_to(net[1], net[0], n_sh)],
                                 axis=-1)
        else:
            x0 = mf
        net[0] = _gru(up["gru0"], net[0], *zqr[0], x0, n_sh)
    if not update:
        return net, None
    return net, _flow_head(up["flow_head"], net[0], n_sh)


def _local_forward(model, n_sh: int, iters: int, variables: Dict,
                   image1: jax.Array, image2: jax.Array,
                   flow_init: jax.Array):
    """The per-shard body under ``shard_map``: the exact op sequence of
    ``RAFTStereo.forward(test_mode=True)`` with every module apply
    replaced by its slab-local mirror above.  All inputs/outputs are
    local H slabs; ``variables`` is replicated."""
    cfg = model.config
    dtype = model.dtype
    params = variables["params"]
    stats = variables.get("batch_stats", {})
    b = image1.shape[0]

    img1 = (2.0 * (image1.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)
    img2 = (2.0 * (image2.astype(jnp.float32) / 255.0) - 1.0).astype(dtype)
    if cfg.input_mode == "sl":
        img1 = _conv(params["sl_proj"]["proj"], img1, 1, 1, n_sh)
        img2 = _conv(params["sl_proj"]["proj"], img2, 1, 1, n_sh)

    outputs = _multi_encoder(params["cnet"], stats.get("cnet", {}),
                             cfg.context_norm, dtype, cfg.n_downsample,
                             img1, cfg.n_gru_layers, 2, n_sh)
    fmaps = _basic_encoder(params["fnet"], stats.get("fnet", {}),
                           "instance", dtype, cfg.n_downsample,
                           jnp.concatenate([img1, img2], axis=0), n_sh)
    fmap1, fmap2 = fmaps[:b], fmaps[b:]

    net_list = [jnp.tanh(o[0]) for o in outputs]
    inp_list = [nn.relu(o[1]) for o in outputs]
    zqr_list = []
    for i, x in enumerate(inp_list):
        hd = cfg.hidden_dims[i]
        y = _conv(params["zqr"][f"zqr{i}"], x, 1, 1, n_sh)
        zqr_list.append((y[..., :hd], y[..., hd:2 * hd], y[..., 2 * hd:]))

    # Corr build AND lookups are H-row-local (the 1-D correlation is
    # along W), so the stock backend runs unchanged on the slab fmaps.
    corr_dtype = (jnp.bfloat16 if cfg.corr_dtype == "bfloat16"
                  else jnp.float32)
    corr_fn = make_corr_fn(spatial_corr_implementation(cfg), fmap1, fmap2,
                           cfg.corr_levels, cfg.corr_radius,
                           dtype=corr_dtype, precision=cfg.corr_precision,
                           out_dtype=dtype)

    up = params["update"]
    h0, w0 = net_list[0].shape[1:3]
    grid = coords_grid_x(b, h0, w0)  # x-only: identical on every row slab
    disp = (jnp.zeros((b, h0, w0, 1), jnp.float32)
            + flow_init.astype(jnp.float32))

    sf = cfg.slow_fast_gru
    n = cfg.n_gru_layers

    def step(carry, _):
        nets, d = carry
        d = lax.stop_gradient(d)
        corr = corr_fn(grid + d)
        flow = jnp.concatenate([d, jnp.zeros_like(d)], axis=-1).astype(dtype)
        nets = list(nets)
        if n == 3 and sf:
            nets, _ = _update_block(up, cfg, dtype, n_sh, nets, zqr_list,
                                    iter2=True, iter1=False, iter0=False,
                                    update=False)
        if n >= 2 and sf:
            nets, _ = _update_block(up, cfg, dtype, n_sh, nets, zqr_list,
                                    iter2=(n == 3), iter1=True, iter0=False,
                                    update=False)
        nets, delta = _update_block(up, cfg, dtype, n_sh, nets, zqr_list,
                                    corr=corr, flow=flow,
                                    iter2=(n == 3), iter1=(n >= 2))
        d = d + delta[..., :1].astype(jnp.float32)
        return (tuple(nets), d), None

    (nets, disp), _ = lax.scan(step, (tuple(net_list), disp), None,
                               length=iters)

    mask = 0.25 * _conv(up["mask_conv2"],
                        nn.relu(_conv(up["mask_conv1"], nets[0], 1, 1, n_sh)),
                        1, 0, n_sh)
    disp_up = _convex_upsample(disp, mask.astype(jnp.float32), cfg.factor,
                               n_sh)
    return disp, disp_up


# ------------------------------------------------------------- public API

def build_spatial_forward(model, mesh: Mesh, iters: int):
    """The sharded forward over ``mesh``: (variables, img1, img2,
    flow_init) -> (disp_low, disp_up), all image-space arguments GLOBAL
    arrays sharded P(None, "space") (mesh.spatial_sharded), variables
    replicated.  Not jitted — wrap with ``jax.jit`` or use the
    ``jitted_spatial_*`` builders."""
    validate_spatial_config(model.config)
    n_sh = int(mesh.shape[SPACE_AXIS])

    def local_fn(variables, image1, image2, flow_init):
        return _local_forward(model, n_sh, iters, variables, image1, image2,
                              flow_init)

    spec = P(None, SPACE_AXIS)
    return shard_map(local_fn, mesh,
                     in_specs=(P(), spec, spec, spec),
                     out_specs=(spec, spec), check_rep=False)


def jitted_spatial_infer(model, mesh: Mesh, iters: int = 32):
    """Compiled sharded test-mode forward, signature-compatible with
    ``RAFTStereo.jitted_infer``: (variables, img1, img2) -> (low, up)."""
    fwd = build_spatial_forward(model, mesh, iters)
    cfg = model.config
    shards = int(mesh.shape[SPACE_AXIS])

    def fn(v, i1, i2):
        b, h, w = i1.shape[:3]
        check_spatial_shape(cfg, shards, h, w)
        f = jnp.zeros((b, h // cfg.factor, w // cfg.factor, 1), jnp.float32)
        return fwd(v, i1, i2, f)

    return jax.jit(fn)


def jitted_spatial_infer_init(model, mesh: Mesh, iters: int = 32):
    """Compiled warm-start sharded forward, signature-compatible with
    ``RAFTStereo.jitted_infer_init``: (variables, img1, img2, flow_init)
    -> (low, up).  Zeros ``flow_init`` reproduces ``jitted_spatial_infer``
    bitwise (same property as the single-device pair)."""
    fwd = build_spatial_forward(model, mesh, iters)
    cfg = model.config
    shards = int(mesh.shape[SPACE_AXIS])

    def fn(v, i1, i2, flow_init):
        check_spatial_shape(cfg, shards, i1.shape[1], i1.shape[2])
        return fwd(v, i1, i2, flow_init)

    return jax.jit(fn)
