"""Multi-host (multi-process) runtime: the distributed backend.

The reference has no multi-node support at all — its only parallelism is
single-process ``nn.DataParallel`` (reference: train_stereo.py:135; SURVEY.md
§2.7).  The TPU-native distributed story needs no hand-written NCCL/MPI layer:
every collective is emitted by XLA from sharding annotations and rides ICI
within a slice and DCN across slices.  What IS needed host-side, and lives
here, is:

* process-group bring-up (``initialize``) — JAX's coordinator handshake,
  auto-configured on TPU pods, explicit host/rank wiring elsewhere;
* per-process input feeding — each host loads only its shard of the global
  batch and assembles a global jax.Array from process-local data.

Single-process runs (tests, one chip) pass through unchanged: ``initialize``
is a no-op without peer configuration and the feeding helpers degrade to
``device_put``.
"""

from __future__ import annotations

import logging
from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS

logger = logging.getLogger(__name__)

__all__ = ["initialize", "is_multiprocess", "process_local_batch",
           "global_batch_from_local"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX process group (idempotent).

    On TPU pods all three arguments come from the environment and may be left
    ``None`` (jax.distributed autodetects); on CPU/GPU clusters pass them
    explicitly.  Calling with everything ``None`` outside a managed TPU/SLURM
    environment is a silent no-op so single-host entry points need no guard.
    """
    global _initialized
    if _initialized:
        return
    # NB: do NOT probe jax.process_count()/jax.devices() here — reading them
    # initializes the XLA backend, after which distributed bring-up is
    # permanently "too late" (the round-1 bug that kept this path untested).
    if (coordinator_address is None and num_processes is None
            and process_id is None):
        import os
        managed = any(v in os.environ for v in
                      ("TPU_WORKER_HOSTNAMES", "TPU_SKYLARK_HOSTS",
                       "MEGASCALE_COORDINATOR_ADDRESS", "SLURM_JOB_ID"))
        if not managed:
            return
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        # Too late (XLA backend already up — e.g. library imported and used
        # before the entry point ran) or coordinator handshake failed.
        # Single-host work proceeds; multi-host callers see the warning.
        logger.warning("distributed init skipped: %s", e)
        return
    _initialized = True
    logger.info("distributed: process %d/%d, %d local / %d global devices",
                jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def process_local_batch(global_batch_size: int) -> Tuple[int, int]:
    """(local_batch_size, sample_offset) for this process.

    Each host's loader reads only its contiguous slice of the global batch —
    the multi-host replacement for the reference's single-process DataLoader.
    """
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by "
            f"{n} processes")
    local = global_batch_size // n
    return local, jax.process_index() * local


def global_batch_from_local(mesh: Mesh, local_batch):
    """Assemble global, ``data``-sharded jax.Arrays from each process's local
    shard (tuple of host arrays with leading local-batch axis).

    Multi-host: wraps ``jax.make_array_from_process_local_data`` so no host
    ever materialises the global batch.  Single-host: plain sharded
    device_put (bitwise-identical layout, same code path for callers).
    """
    s = NamedSharding(mesh, P(DATA_AXIS))
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, s), local_batch)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(s, x), local_batch)
