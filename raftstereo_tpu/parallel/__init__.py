"""Parallelism layer: device meshes, shardings, and sharded step compilation.

The reference's only parallelism is single-process ``nn.DataParallel``
(reference: train_stereo.py:135) — replicate weights, scatter the batch,
gather outputs.  Here the same capability (and beyond: multi-host) is
expressed the TPU way: a ``jax.sharding.Mesh`` plus sharding annotations on
``jax.jit``; XLA inserts the gradient all-reduce over ICI/DCN (SURVEY.md §2.7).
"""

from .distributed import (global_batch_from_local, initialize,
                          is_multiprocess, process_local_batch)
from .mesh import (DATA_AXIS, SPACE_AXIS, batch_sharded, make_mesh,
                   replica_devices, replicated, shard_batch,
                   spatial_sharded)
from .spatial import (SpatialShardingUnsupported, check_spatial_shape,
                      halo_exchange, jitted_spatial_infer,
                      jitted_spatial_infer_init, spatial_mesh,
                      spatial_row_multiple, validate_spatial_config)

__all__ = [
    "DATA_AXIS", "SPACE_AXIS", "make_mesh", "replicated", "batch_sharded",
    "spatial_sharded", "shard_batch", "replica_devices",
    "initialize", "is_multiprocess", "process_local_batch",
    "global_batch_from_local",
    "SpatialShardingUnsupported", "check_spatial_shape", "halo_exchange",
    "jitted_spatial_infer", "jitted_spatial_infer_init", "spatial_mesh",
    "spatial_row_multiple", "validate_spatial_config",
]
