"""Trace-time mesh context for mesh-partitionable Pallas backends.

Pallas kernels compile to XLA custom calls, which the SPMD partitioner
cannot split on its own — without help, a Pallas corr backend under a
multi-device ``jit`` would be a scaling boundary (the round-1 state).  The
kernels' grids are per-(B*H)-row independent (the same independence the
reference's CUDA kernel exploits: one thread block per row,
sampler/sampler_kernel.cu:19-60), so batch- and height-sharding need no
cross-shard communication at all: the right program is "run the same kernel
on each shard's rows", i.e. ``shard_map``.

``shard_map`` needs the concrete mesh at trace time, which the functional
ops layer can't see from inside ``jit``.  This context hands it down:
entry points that own a mesh (train loop, Evaluator, dryrun) wrap their
trace in ``use_corr_mesh(mesh)``; ``ops/corr.py`` consults
``active_corr_mesh()`` when building a Pallas backend and wraps
construction + per-iteration lookups in ``shard_map`` over the mesh's
(data, space) axes.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

from jax.sharding import Mesh

_state = threading.local()


@contextmanager
def use_corr_mesh(mesh: Optional[Mesh]):
    """Make ``mesh`` visible to Pallas corr-backend construction during
    tracing.  ``None`` is allowed (no-op) so callers can pass their
    maybe-mesh straight through."""
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield
    finally:
        _state.mesh = prev


def active_corr_mesh() -> Optional[Mesh]:
    """The mesh set by the innermost ``use_corr_mesh``, if any (and only if
    it actually has more than one device — a trivial 1x1 mesh means plain
    single-device lowering is the right program)."""
    mesh = getattr(_state, "mesh", None)
    if mesh is not None and mesh.size > 1:
        return mesh
    return None
