"""Device mesh + sharding vocabulary.

Two mesh axes cover this framework's scaling dimensions:

* ``data``  — batch sharding for training (the DataParallel equivalent,
  reference: train_stereo.py:135).  Gradients are partial-summed per shard and
  all-reduced by XLA over ICI within a slice / DCN across slices.
* ``space`` — image-height sharding for high-resolution inference.  The
  reference's answer to big images is an O(H*W) correlation backend and a
  bigger downsample factor (reference: README.md:111,121); sharding H over
  chips is the TPU answer.  The canonical implementation is
  ``parallel/spatial.py``: the whole forward runs under ``shard_map`` on a
  ``(1, N)`` mesh with EXPLICIT ``ppermute`` halo exchange at every conv's
  slab boundary — the 1-D correlation is along W (each H shard's epipolar
  lines are self-contained), so the halos are the only collectives until
  the final gather.  (An earlier revision of this docstring claimed XLA's
  SPMD partitioner inserts the halos automatically under plain ``jit`` —
  true, but that path neither guarantees bitwise parity with the
  single-device program nor keeps the corr volume row-local by
  construction, which is why the subsystem owns its collectives.)

Everything here is plain ``jax.sharding``; no wrappers around jit.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPACE_AXIS = "space"


def make_mesh(data: Optional[int] = None, space: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a (data, space) mesh over the given (default: all) devices.

    ``data=None`` uses every device not consumed by ``space``.  A laptop/CI
    run with one device yields a trivial 1x1 mesh, so all sharded code paths
    are identical from 1 chip to a pod.
    """
    devices = list(devices if devices is not None else jax.devices())
    total = len(devices)
    if space <= 0:
        raise ValueError(f"space must be >= 1, got {space}")
    if data is None:
        if total % space:
            raise ValueError(
                f"{total} devices not divisible by space={space}; pass data= "
                f"explicitly to use a subset")
        data = max(total // space, 1)
    use = data * space
    if use > total:
        raise ValueError(
            f"mesh {data}x{space} needs {use} devices, have {total}")
    arr = np.asarray(devices[:use], dtype=object).reshape(data, space)
    return Mesh(arr, (DATA_AXIS, SPACE_AXIS))


def replica_devices(n: Optional[int] = None,
                    devices: Optional[Sequence] = None) -> list:
    """Devices for N independent serving-engine replicas — the data axis
    of an (n, 1) mesh, so replica placement follows the same device
    order/layout training's data-parallel sharding uses (serve/cluster/
    instantiates one ``BatchEngine`` per returned device).

    ``n=None`` replicates over every visible device.  On the CPU host
    platform, ``--xla_force_host_platform_device_count=N`` fans the host
    out into N virtual devices, so multi-replica serving runs (and is
    tested) without a pod — same answer as tests/conftest.py.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n is None:
        n = len(devices)
    if n < 1:
        raise ValueError(f"replicas must be >= 1, got {n}")
    if n > len(devices):
        raise ValueError(
            f"{n} replicas need {n} devices, have {len(devices)} "
            f"(on CPU, raise --xla_force_host_platform_device_count)")
    mesh = make_mesh(data=n, space=1, devices=devices[:n])
    return [mesh.devices[i, 0] for i in range(n)]


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (weights, optimizer state, scalars)."""
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh) -> NamedSharding:
    """Shard axis 0 (batch) across the ``data`` axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def spatial_sharded(mesh: Mesh) -> NamedSharding:
    """Shard axis 1 (image height H, NHWC layout) across the ``space``
    axis — the in/out sharding of the spatial-inference subsystem
    (``parallel/spatial.py``; its ``shard_map`` specs are the
    ``PartitionSpec`` twin of this ``NamedSharding``).  Batch stays
    unsharded: the spatial path is single-request by design, the whole
    mesh belongs to one pair."""
    return NamedSharding(mesh, P(None, SPACE_AXIS))


def shard_batch(mesh: Mesh, batch):
    """Place a host batch (tuple of arrays, leading batch axis) on the mesh."""
    s = batch_sharded(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, s), batch)
